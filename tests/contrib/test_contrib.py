"""Contrib module tests (mirrors ref apex/contrib/test/* strategy: parity
vs plain implementations on small shapes)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.conv_bias_relu import ConvBias, ConvBiasMaskReLU, ConvBiasReLU
from apex_tpu.contrib.fmha import fmha, fmha_packed_qkv
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.layer_norm import FastLayerNorm, fast_layer_norm
from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from apex_tpu.contrib.peer_memory import halo_exchange_1d
from apex_tpu.contrib.sparsity import ASP, create_mask, mn_1d_mask
from apex_tpu.contrib.optimizers import distributed_fused_adam
from apex_tpu.contrib.transducer import TransducerJoint, transducer_loss
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers import fused_adam


class TestXentropy:
    def test_matches_plain_ce(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (8, 32))
        labels = jax.random.randint(jax.random.PRNGKey(1), (8,), 1, 32)
        got = softmax_cross_entropy_loss(logits, labels)
        want = -jnp.take_along_axis(jax.nn.log_softmax(logits),
                                    labels[:, None], 1)[:, 0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)

    def test_smoothing_and_padding(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
        labels = jnp.array([0, 3, 5, 7])  # first = padding_idx
        loss = softmax_cross_entropy_loss(logits, labels, smoothing=0.1)
        assert float(loss[0]) == 0.0
        v = logits.shape[-1]
        lp = jax.nn.log_softmax(logits)
        want = -(0.9 * jnp.take_along_axis(lp, labels[:, None], 1)[:, 0]
                 + 0.1 * jnp.mean(lp, axis=-1))
        np.testing.assert_allclose(np.asarray(loss[1:]), np.asarray(want[1:]),
                                   rtol=1e-5)

    def test_grad_matches_autodiff(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
        labels = jnp.array([2, 3, 0, 7])

        def fused(lg):
            return jnp.sum(softmax_cross_entropy_loss(lg, labels,
                                                      smoothing=0.2))

        def plain(lg):
            lp = jax.nn.log_softmax(lg)
            nll = -jnp.take_along_axis(lp, labels[:, None], 1)[:, 0]
            sm = -jnp.mean(lp, axis=-1)
            per = 0.8 * nll + 0.2 * sm
            return jnp.sum(jnp.where(labels == 0, 0.0, per))

        np.testing.assert_allclose(np.asarray(jax.grad(fused)(logits)),
                                   np.asarray(jax.grad(plain)(logits)),
                                   rtol=1e-4, atol=1e-5)


class TestClipFocal:
    def test_clip_grad_norm(self):
        g = {"a": jnp.full((4,), 3.0), "b": jnp.full((9,), 4.0)}
        clipped, norm = clip_grad_norm_(g, 5.0)
        np.testing.assert_allclose(float(norm), np.sqrt(4 * 9 + 9 * 16),
                                   rtol=1e-5)
        total = jnp.sqrt(sum(jnp.sum(x ** 2) for x in
                             jax.tree_util.tree_leaves(clipped)))
        np.testing.assert_allclose(float(total), 5.0, rtol=1e-4)

    def test_focal_loss_reduces_to_weighted_ce_at_gamma0(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (6, 10))
        targets = jnp.array([0, 1, 2, -1, 4, 5])
        lf = focal_loss(logits, targets, jnp.asarray(5.0), 10, alpha=0.25,
                        gamma=0.0)
        onehot = jax.nn.one_hot(jnp.maximum(targets, 0), 10)
        onehot = jnp.where((targets >= 0)[:, None], onehot, 0.0)
        a = 0.25 * onehot + 0.75 * (1 - onehot)
        bce = a * (jnp.maximum(logits, 0) - logits * onehot
                   + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        np.testing.assert_allclose(float(lf), float(jnp.sum(bce) / 5.0),
                                   rtol=1e-5)

    def test_focal_gamma_downweights_easy(self):
        logits = jnp.array([[8.0, -8.0]])  # confidently correct for class 0
        t = jnp.array([0])
        easy = focal_loss(logits, t, jnp.asarray(1.0), 2, 0.5, 2.0)
        hard = focal_loss(-logits, t, jnp.asarray(1.0), 2, 0.5, 2.0)
        assert float(easy) < float(hard) / 100


class TestLayerNormConv:
    def test_fast_layer_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
        g, b = jnp.ones(64) * 1.5, jnp.full((64,), 0.25)
        got = fast_layer_norm(x, g, b)
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        want = (x - mu) / jnp.sqrt(var + 1e-5) * g + b
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
        ln = FastLayerNorm(64)
        v = ln.init(jax.random.PRNGKey(1), x)
        np.testing.assert_allclose(np.asarray(ln.apply(v, x)),
                                   np.asarray(fast_layer_norm(
                                       x, jnp.ones(64), jnp.zeros(64))),
                                   rtol=1e-4, atol=1e-5)

    def test_conv_bias_relu(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 5)) * 0.2
        b = jnp.linspace(-1, 1, 5)
        y = ConvBiasReLU(x, w, b, padding=1, stride=1)
        assert y.shape == (2, 8, 8, 5)
        assert float(jnp.min(y)) >= 0.0
        y2 = ConvBias(x, w, b, padding=1, stride=2)
        assert y2.shape == (2, 4, 4, 5)
        mask = jnp.zeros((2, 8, 8, 5)).at[:, :4].set(1.0)
        y3 = ConvBiasMaskReLU(x, w, b, mask, padding=1, stride=1)
        np.testing.assert_allclose(np.asarray(y3[:, 4:]), 0.0)

    def test_groupbn_fuse_relu_and_addrelu(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
        bn = BatchNorm2d_NHWC(8, fuse_relu=True, bn_group=1)
        v = bn.init(jax.random.PRNGKey(1), x)
        y = bn.apply(v, x, mutable=["batch_stats"])[0]
        assert float(jnp.min(y)) >= 0.0
        z = jnp.ones_like(x)
        bn2 = BatchNorm2d_NHWC(8)
        v2 = bn2.init(jax.random.PRNGKey(1), x)
        y2 = bn2.apply(v2, x, z, mutable=["batch_stats"])[0]
        assert float(jnp.min(y2)) >= 0.0  # add+relu path


class TestAttention:
    def test_fmha_matches_softmax_attention(self):
        b, s, h, d = 2, 64, 4, 32
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, h, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d))
        got = fmha(q, k, v, causal=True)
        s_ = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
        mask = jnp.tril(jnp.ones((s, s), bool))
        s_ = jnp.where(mask, s_, -1e30)
        want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s_), v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_fmha_gqa_matches_repeat(self):
        b, s, h, hkv, d = 2, 32, 8, 2, 16
        q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d))
        got = fmha(q, k, v, causal=True)
        kr = jnp.repeat(k, h // hkv, axis=2)
        vr = jnp.repeat(v, h // hkv, axis=2)
        want = fmha(q, kr, vr, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_self_mha_key_padding_excludes_keys(self):
        """Changing a PADDED key must not change any output; semantics match
        a manual pre-softmax key mask."""
        s, b, h = 8, 2, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        mask = jnp.zeros((b, s), bool).at[:, 6:].set(True)
        m = SelfMultiheadAttn(hidden_dim=h, heads=2)
        v = m.init(jax.random.PRNGKey(1), x)
        y1 = m.apply(v, x, key_padding_mask=mask)
        x2 = x.at[7].add(100.0)  # perturb a padded position's input...         # (its QUERY row changes, but other rows must not)
        y2 = m.apply(v, x2, key_padding_mask=mask)
        np.testing.assert_allclose(np.asarray(y1[:6]), np.asarray(y2[:6]),
                                   rtol=1e-4, atol=1e-5)

    def test_self_mha_bool_attn_mask_matches_manual(self):
        """Causal bool attn_mask (True = masked) must match manually-masked
        softmax attention (ref self_multihead_attn.py:144 mask support)."""
        s, b, h, heads = 8, 2, 16, 2
        d = h // heads
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        causal = jnp.triu(jnp.ones((s, s), bool), k=1)
        m = SelfMultiheadAttn(hidden_dim=h, heads=heads)
        var = m.init(jax.random.PRNGKey(1), x)
        got = m.apply(var, x, attn_mask=causal)

        # manual reference: same params, explicit masked softmax
        qkv = x @ var["params"]["qkv_proj"]["kernel"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def hf(t):
            return t.transpose(1, 0, 2).reshape(b, s, heads, d)

        q, k, v = hf(q), hf(k), hf(v)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * d ** -0.5
        scores = jnp.where(causal[None, None], -jnp.inf, scores)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
        want = (o.reshape(b, s, h).transpose(1, 0, 2)
                @ var["params"]["out_proj"]["kernel"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_self_mha_additive_attn_mask(self):
        """A -inf additive float mask behaves like the bool mask."""
        s, b, h = 8, 2, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        causal_bool = jnp.triu(jnp.ones((s, s), bool), k=1)
        causal_add = jnp.where(causal_bool, -jnp.inf, 0.0).astype(x.dtype)
        m = SelfMultiheadAttn(hidden_dim=h, heads=2)
        var = m.init(jax.random.PRNGKey(1), x)
        y_bool = m.apply(var, x, attn_mask=causal_bool)
        y_add = m.apply(var, x, attn_mask=causal_add)
        np.testing.assert_allclose(np.asarray(y_bool), np.asarray(y_add),
                                   rtol=1e-5, atol=1e-6)

    def test_self_mha_int_attn_mask_treated_as_bool(self):
        """torch-style byte masks (1 = masked) must behave like bool masks,
        not be added to the scores."""
        s, b, h = 8, 2, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        causal_bool = jnp.triu(jnp.ones((s, s), bool), k=1)
        causal_int = causal_bool.astype(jnp.uint8)
        m = SelfMultiheadAttn(hidden_dim=h, heads=2)
        var = m.init(jax.random.PRNGKey(1), x)
        np.testing.assert_allclose(
            np.asarray(m.apply(var, x, attn_mask=causal_int)),
            np.asarray(m.apply(var, x, attn_mask=causal_bool)),
            rtol=1e-6, atol=1e-7)

    def test_self_mha_attn_mask_with_key_padding(self):
        """attn_mask composes with key_padding_mask."""
        s, b, h = 8, 2, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        causal = jnp.triu(jnp.ones((s, s), bool), k=1)
        pad = jnp.zeros((b, s), bool).at[:, 6:].set(True)
        m = SelfMultiheadAttn(hidden_dim=h, heads=2)
        var = m.init(jax.random.PRNGKey(1), x)
        y1 = m.apply(var, x, key_padding_mask=pad, attn_mask=causal)
        x2 = x.at[7].add(100.0)  # padded key perturbation is invisible
        y2 = m.apply(var, x2, key_padding_mask=pad, attn_mask=causal)
        np.testing.assert_allclose(np.asarray(y1[:6]), np.asarray(y2[:6]),
                                   rtol=1e-4, atol=1e-5)

    def test_fmha_packed(self):
        qkv = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 3, 4, 8))
        out = fmha_packed_qkv(qkv)
        assert out.shape == (2, 16, 4, 8)

    def test_fmha_varlen_masks_padding(self):
        """cu_seqlens/seqlens must exclude padded keys (ref fmha varlen):
        output for the valid prefix equals attention over the truncated
        sequence, and padded query rows are zeroed."""
        from apex_tpu.contrib.fmha import FMHAFun

        b, s, h, d = 2, 12, 2, 8
        qkv = jax.random.normal(jax.random.PRNGKey(0), (b, s, 3, h, d))
        seqlens = jnp.array([12, 7])
        cu = jnp.array([0, 12, 19])
        out = FMHAFun.apply(qkv, cu_seqlens=cu)
        out2 = FMHAFun.apply(qkv, seqlens=seqlens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                                   rtol=1e-6)
        # batch 1, valid rows == attention over the 7-token slice
        want = fmha(qkv[1:2, :7, 0], qkv[1:2, :7, 1], qkv[1:2, :7, 2])
        np.testing.assert_allclose(np.asarray(out[1, :7]),
                                   np.asarray(want[0]), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1, 7:]), 0.0)
        # full-length batch 0 matches the unmasked kernel
        full = fmha(qkv[0:1, :, 0], qkv[0:1, :, 1], qkv[0:1, :, 2])
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(full[0]),
                                   rtol=1e-4, atol=1e-5)

    def test_self_mha_shapes_and_norm_add(self):
        s, b, h = 12, 2, 32
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        for norm_add in (False, True):
            m = SelfMultiheadAttn(hidden_dim=h, heads=4,
                                  include_norm_add=norm_add)
            v = m.init(jax.random.PRNGKey(1), x)
            y = m.apply(v, x)
            assert y.shape == (s, b, h)

    def test_encdec_mha(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (6, 2, 16))
        kv = jax.random.normal(jax.random.PRNGKey(1), (9, 2, 16))
        m = EncdecMultiheadAttn(hidden_dim=16, heads=2)
        v = m.init(jax.random.PRNGKey(2), q, kv)
        y = m.apply(v, q, kv)
        assert y.shape == (6, 2, 16)


class TestSparsity:
    def test_mn_1d_mask_density_and_selection(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (16, 32))
        m = mn_1d_mask(w, 4, 2)
        assert float(jnp.mean(m.astype(jnp.float32))) == 0.5
        groups = jnp.abs(w).reshape(16, 8, 4)
        kept = jnp.abs(w * m).reshape(16, 8, 4)
        # the kept magnitudes are the top-2 of each group
        np.testing.assert_allclose(
            np.asarray(jnp.sort(kept, -1)[..., 2:]),
            np.asarray(jnp.sort(groups, -1)[..., 2:]), rtol=1e-6)

    def test_asp_masked_training_preserves_sparsity(self):
        params = {"dense": {"w": jax.random.normal(jax.random.PRNGKey(0),
                                                   (8, 16))}}
        params, masks = ASP.init_model_for_pruning(params)
        tx = ASP.init_optimizer_for_pruning(fused_adam(lr=0.1), masks)
        state = tx.init(params)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))

        def loss(p):
            return jnp.mean((x @ p["dense"]["w"] - 1.0) ** 2)

        import optax
        for _ in range(3):
            g = jax.grad(loss)(params)
            u, state = tx.update(g, state, params)
            params = optax.apply_updates(params, u)
        w = params["dense"]["w"]
        density = float(jnp.mean((w != 0).astype(jnp.float32)))
        assert density <= 0.5 + 1e-6

    def test_2d_pattern(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (16, 16))
        m = create_mask(w, "m4n2_2d_best")
        assert float(jnp.mean(m.astype(jnp.float32))) <= 0.5

    def test_permutation_search_beats_naive(self):
        # adversarial layout (ref permutation_lib.py's motivating case):
        # all big channels packed into the same m=4 groups, so naive m4n2
        # must drop half of them; a permutation spreads them out
        from apex_tpu.contrib.sparsity import (
            find_channel_permutation,
            permuted_mn_mask,
            retained_magnitude,
        )

        rng = np.random.default_rng(0)
        big = rng.normal(size=(8, 8)) * 10.0
        small = rng.normal(size=(8, 24)) * 0.1
        w = jnp.asarray(np.concatenate([big, small], axis=1))

        naive = mn_1d_mask(w, 4, 2)
        mask, perm = permuted_mn_mask(w, 4, 2)
        r_naive = retained_magnitude(w, naive)
        r_perm = retained_magnitude(w, mask)
        assert r_perm > r_naive, (r_perm, r_naive)
        # permuted mask is still 2-of-4 under the found permutation
        perm_mask = np.asarray(mask)[:, perm].reshape(8, 8, 4)
        assert (perm_mask.sum(-1) == 2).all()
        assert sorted(perm.tolist()) == list(range(32))

    def test_permutation_identity_on_uniform(self):
        # permutation can never LOSE magnitude vs naive
        from apex_tpu.contrib.sparsity import (
            permuted_mn_mask,
            retained_magnitude,
        )

        w = jax.random.normal(jax.random.PRNGKey(3), (16, 32))
        naive = mn_1d_mask(w, 4, 2)
        mask, _ = permuted_mn_mask(w, 4, 2)
        assert (retained_magnitude(w, mask)
                >= retained_magnitude(w, naive) - 1e-6)

    def test_asp_allow_permutation(self):
        params = {"w": jax.random.normal(jax.random.PRNGKey(4), (8, 16))}
        masks = ASP.compute_sparse_masks(params, allow_permutation=True)
        dens = float(jnp.mean(masks["w"].astype(jnp.float32)))
        assert dens == 0.5


class TestDistributedFusedAdam:
    def test_matches_plain_adam(self):
        """ZeRO-sharded update == replicated fused adam update."""
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (37,)),
                  "b": jnp.ones((5,))}
        grads = {"w": jnp.full((37,), 0.5), "b": jnp.full((5,), -0.25)}

        tx = distributed_fused_adam(lr=1e-2, axis_name="dp")

        def run(params, grads):
            state = tx.init(params)
            updates, _ = tx.update(grads, state, params)
            return updates

        got = shard_map(run, mesh=mesh, in_specs=(P(), P()), out_specs=P())(params, grads)

        ref_tx = fused_adam(lr=1e-2)
        st = ref_tx.init(params)
        want, _ = ref_tx.update(grads, st, params)
        for k in params:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(want[k]), rtol=1e-5,
                                       atol=1e-6)


class TestTransducer:
    def test_joint(self):
        f = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 8))
        g = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
        h = TransducerJoint()(f, g)
        assert h.shape == (2, 5, 3, 8)
        np.testing.assert_allclose(np.asarray(h[0, 2, 1]),
                                   np.asarray(f[0, 2] + g[0, 1]), rtol=1e-6)
        hr = TransducerJoint(relu=True)(f, g)
        assert float(jnp.min(hr)) >= 0.0

    def test_loss_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        B, T, U, V = 3, 6, 4, 8
        logits = rng.randn(B, T, U + 1, V).astype(np.float32)
        targets = rng.randint(1, V, (B, U))
        f_len = np.array([6, 5, 4])
        y_len = np.array([4, 3, 2])
        got = np.asarray(transducer_loss(
            jnp.asarray(logits), jnp.asarray(targets), jnp.asarray(f_len),
            jnp.asarray(y_len)))

        lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))

        def brute(lp, tg, T, U):
            NEG = -1e30
            alpha = np.full((T, U + 1), NEG)
            alpha[0, 0] = 0.0
            for t in range(T):
                for u in range(U + 1):
                    c = []
                    if t > 0:
                        c.append(alpha[t - 1, u] + lp[t - 1, u, 0])
                    if u > 0:
                        c.append(alpha[t, u - 1] + lp[t, u - 1, tg[u - 1]])
                    if c:
                        m = max(c)
                        if m > NEG / 2:
                            alpha[t, u] = m + np.log(
                                sum(np.exp(x - m) for x in c))
            return -(alpha[T - 1, U] + lp[T - 1, U, 0])

        want = np.array([brute(lp[b], targets[b], f_len[b], y_len[b])
                         for b in range(B)])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_loss_grad_finite(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 4, 6))
        targets = jnp.array([[1, 2, 3], [2, 3, 1]])
        g = jax.grad(lambda lg: jnp.sum(transducer_loss(
            lg, targets, jnp.array([5, 4]), jnp.array([3, 2]))))(logits)
        assert np.isfinite(np.asarray(g)).all()

    @staticmethod
    def _pack(padded, f_len, g_len):
        """Reference packed layout: each batch's valid [f_len, g_len]
        block, row-major, concatenated."""
        rows = [np.asarray(padded[b, :f_len[b], :g_len[b]]).reshape(
            f_len[b] * g_len[b], -1) for b in range(padded.shape[0])]
        return np.concatenate(rows, axis=0)

    def test_joint_pack_output_matches_reference_layout(self):
        f = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 8))
        g = jax.random.normal(jax.random.PRNGKey(1), (3, 4, 8))
        f_len = jnp.array([5, 3, 4])
        g_len = jnp.array([4, 2, 3])
        batch_offset = jnp.cumsum(f_len * g_len)
        packed_batch = int(batch_offset[-1])
        packed = TransducerJoint(pack_output=True)(
            f, g, f_len, g_len, batch_offset=batch_offset,
            packed_batch=packed_batch)
        assert packed.shape == (packed_batch, 8)
        padded = TransducerJoint()(f, g)
        want = self._pack(padded, np.asarray(f_len), np.asarray(g_len))
        np.testing.assert_allclose(np.asarray(packed), want, rtol=1e-6)

    def test_packed_loss_matches_padded(self):
        rng = np.random.RandomState(1)
        B, T, U, V = 3, 6, 4, 8
        logits = rng.randn(B, T, U + 1, V).astype(np.float32)
        targets = jnp.asarray(rng.randint(1, V, (B, U)))
        f_len = jnp.array([6, 5, 4])
        y_len = jnp.array([4, 3, 2])
        want = transducer_loss(jnp.asarray(logits), targets, f_len, y_len)
        g_len = y_len + 1
        batch_offset = jnp.cumsum(f_len * g_len)
        packed = jnp.asarray(self._pack(
            logits, np.asarray(f_len), np.asarray(g_len)))
        got = transducer_loss(
            packed, targets, f_len, y_len, packed_input=True,
            batch_offset=batch_offset, max_f_len=T)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        # grads flow back through the unpack gather to the packed rows
        grad = jax.grad(lambda x: jnp.sum(transducer_loss(
            x, targets, f_len, y_len, packed_input=True,
            batch_offset=batch_offset, max_f_len=T)))(packed)
        assert np.isfinite(np.asarray(grad)).all()
        assert float(jnp.abs(grad).sum()) > 0


class TestHaloExchange:
    def test_halo_rows_move_to_neighbours(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("spatial",))
        hh = 1
        # global map [N=1, H=16, W=2, C=3], H sharded 4 ways (4 rows/rank)
        x = jnp.arange(16 * 2 * 3, dtype=jnp.float32).reshape(1, 16, 2, 3)

        def run(x_local):
            pad = [(0, 0)] * x_local.ndim
            pad[1] = (hh, hh)
            y = jnp.pad(x_local, pad)
            y = halo_exchange_1d(y, hh, "spatial", h_dim=1)
            return y[None]  # stack per-rank padded slabs on a new axis

        got = shard_map(run, mesh=mesh, in_specs=P(None, "spatial"),
                        out_specs=P("spatial"))(x)
        got = np.asarray(got)          # [4, 1, 6, 2, 3]
        slabs = np.asarray(x).reshape(4, 4, 2, 3)
        # rank r's top margin row == rank r-1's last row; bottom == r+1's first
        for r in range(1, 4):
            np.testing.assert_allclose(got[r, 0, 0], slabs[r - 1, -1])
        for r in range(0, 3):
            np.testing.assert_allclose(got[r, 0, -1], slabs[r + 1, 0])


def test_fmha_varlen_empty_sequence_grads_finite():
    """A zero-length sequence (legal in reference varlen batching) must
    give finite (zero) grads, not NaN."""
    from apex_tpu.contrib.fmha import fmha_packed_qkv

    qkv = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 3, 2, 4))
    seqlens = jnp.array([8, 0])

    def loss(qkv):
        return jnp.sum(fmha_packed_qkv(qkv, seqlens=seqlens) ** 2)

    g = jax.grad(loss)(qkv)
    assert np.isfinite(np.asarray(g)).all()
    np.testing.assert_allclose(np.asarray(g[1]), 0.0)  # empty seq: no grad


def _varlen_reference(q, k, v, seqlens):
    """Independent dense reference for varlen attention."""
    b, s, h, d = q.shape
    if k.shape[2] != h:
        k = jnp.repeat(k, h // k.shape[2], axis=2)
        v = jnp.repeat(v, h // v.shape[2], axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    ok = jnp.arange(s)[None, :] < seqlens[:, None]
    scores = jnp.where(ok[:, None, None, :], scores, -1e30)
    out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
    return jnp.where(ok[:, :, None, None], out, 0.0)


def test_fmha_varlen_gqa_matches_reference():
    from apex_tpu.ops.flash_attention import flash_attention

    b, s, h, d = 2, 8, 4, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, h // 2, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, h // 2, d))
    seqlens = jnp.array([8, 5])
    got = flash_attention(q, k, v, kv_lens=seqlens)
    want = _varlen_reference(q, k, v, seqlens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-6)


def test_fmha_varlen_pallas_kernel_matches():
    """The in-kernel kv_lens bound (interpret mode) must match the jnp
    fallback, forward and backward, including an empty sequence."""
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    b, s, h, d = 2, 64, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, h, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d))
    seqlens = jnp.array([64, 0])

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, kv_lens=seqlens) ** 2)

    ref_out = flash_attention(q, k, v, kv_lens=seqlens)
    ref_g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    with pallas_config.force("interpret"):
        out = flash_attention(q, k, v, kv_lens=seqlens)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-5)
    for name, a, bb in zip("qkv", g, ref_g):
        assert np.isfinite(np.asarray(a)).all(), f"d{name} not finite"
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")
    # ragged middle length through the blocked kernel too
    seqlens2 = jnp.array([37, 64])

    with pallas_config.force("interpret"):
        out2 = flash_attention(q, k, v, kv_lens=seqlens2)
    want2 = _varlen_reference(q, k, v, seqlens2)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(want2),
                               rtol=1e-4, atol=1e-5)


class TestMaskSoftmaxDropout:
    """ref contrib/multihead_attn/mask_softmax_dropout_func.py — the
    standalone fused mask+softmax+dropout op."""

    def test_bool_and_additive_masks_agree(self):
        from apex_tpu.contrib.multihead_attn import (MaskSoftmaxDropout,
                                                     mask_softmax_dropout)

        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16))
        pm = jnp.zeros((2, 1, 16), bool).at[:, :, 12:].set(True)
        out = mask_softmax_dropout(x, pm, heads=2)
        assert out.shape == (4, 8, 16)
        # masked keys get zero probability; rows renormalize
        assert float(jnp.abs(out[:, :, 12:]).sum()) == 0.0
        np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0, rtol=1e-5)
        additive = jnp.where(pm, -1e9, 0.0)
        out2 = mask_softmax_dropout(x, additive, heads=2,
                                    mask_additive=True)
        np.testing.assert_allclose(np.asarray(out2), np.asarray(out),
                                   atol=1e-5)
        # Function.apply-shaped class wrapper
        out3 = MaskSoftmaxDropout()(True, 2, x, pm, False, 0.0)
        np.testing.assert_allclose(np.asarray(out3), np.asarray(out),
                                   atol=1e-6)

    def test_dropout_and_grads(self):
        from apex_tpu.contrib.multihead_attn import mask_softmax_dropout

        x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 8))
        rng = jax.random.PRNGKey(2)
        out = mask_softmax_dropout(x, None, heads=2, dropout_prob=0.5,
                                   dropout_rng=rng)
        zeros = float((out == 0).mean())
        assert 0.2 < zeros < 0.8  # ~half dropped
        # eval mode: dropout off regardless of prob
        out_eval = mask_softmax_dropout(x, None, heads=2,
                                        dropout_prob=0.5,
                                        is_training=False)
        np.testing.assert_allclose(np.asarray(out_eval.sum(-1)), 1.0,
                                   rtol=1e-5)
        g = jax.grad(lambda x: jnp.sum(mask_softmax_dropout(
            x, None, heads=2) ** 2))(x)
        assert np.isfinite(np.asarray(g)).all()
        with pytest.raises(ValueError, match="divisible"):
            mask_softmax_dropout(x, None, heads=3)


class TestHaloExchangers:
    """ref contrib/bottleneck/halo_exchangers.py: every transport must
    produce the same neighbor shift."""

    def test_sendrecv_allgather_agree(self):
        from apex_tpu.contrib.halo_exchangers import (
            HaloExchangerAllGather, HaloExchangerNoComm,
            HaloExchangerPeer, HaloExchangerSendRecv)

        mesh = Mesh(np.array(jax.devices()[:4]), ("spatial",))
        # per-rank distinct edges: [4, rows, C]
        left = jnp.arange(4 * 2 * 3, dtype=jnp.float32).reshape(4, 2, 3)
        right = left + 100.0

        def run(exchanger):
            def f(le, re):
                li, ri = exchanger.left_right_halo_exchange(le[0], re[0])
                return li[None], ri[None]
            return shard_map(f, mesh=mesh,
                             in_specs=(P("spatial"), P("spatial")),
                             out_specs=(P("spatial"), P("spatial")))(
                                 left, right)

        li_sr, ri_sr = run(HaloExchangerSendRecv())
        li_ag, ri_ag = run(HaloExchangerAllGather())
        li_peer, ri_peer = run(HaloExchangerPeer())
        np.testing.assert_allclose(np.asarray(li_sr), np.asarray(li_ag))
        np.testing.assert_allclose(np.asarray(ri_sr), np.asarray(ri_ag))
        np.testing.assert_allclose(np.asarray(li_sr), np.asarray(li_peer))
        # rank r's left input = rank r-1's right edge; rank 0 zeros
        np.testing.assert_allclose(np.asarray(li_sr[0]), 0.0)
        np.testing.assert_allclose(np.asarray(li_sr[1:]),
                                   np.asarray(right[:-1]))
        # rank r's right input = rank r+1's left edge; last rank zeros
        np.testing.assert_allclose(np.asarray(ri_sr[:-1]),
                                   np.asarray(left[1:]))
        np.testing.assert_allclose(np.asarray(ri_sr[-1]), 0.0)
        # no-comm: swapped self-edges, no collective
        li_nc, ri_nc = run(HaloExchangerNoComm())
        np.testing.assert_allclose(np.asarray(li_nc), np.asarray(right))
        np.testing.assert_allclose(np.asarray(ri_nc), np.asarray(left))


def test_frozen_batchnorm2d():
    """ref bottleneck.py FrozenBatchNorm2d: fixed stats fold to one
    scale/bias affine."""
    from apex_tpu.contrib.bottleneck import FrozenBatchNorm2d

    bn = FrozenBatchNorm2d(3)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 3))
    v = bn.init(jax.random.PRNGKey(1), x)
    # identity up to eps at default buffers
    np.testing.assert_allclose(np.asarray(bn.apply(v, x)), np.asarray(x),
                               rtol=1e-4, atol=1e-4)
    v2 = {"frozen": {"weight": jnp.full((3,), 2.0),
                     "bias": jnp.ones((3,)),
                     "running_mean": jnp.full((3,), 0.5),
                     "running_var": jnp.full((3,), 4.0)}}
    y = bn.apply(v2, x)
    want = (np.asarray(x) - 0.5) / np.sqrt(4.0 + 1e-5) * 2.0 + 1.0
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5)
    scale, bias = bn.apply(v2, method="get_scale_bias", nhwc=True)
    assert scale.shape == (1, 1, 1, 3)
    np.testing.assert_allclose(np.asarray(scale[0, 0, 0]),
                               2.0 / np.sqrt(4.0 + 1e-5), rtol=1e-6)
    # NCHW layout broadcast
    xc = jnp.moveaxis(x, -1, 1)
    yc = bn.apply(v2, xc, nhwc=False)
    np.testing.assert_allclose(np.asarray(jnp.moveaxis(yc, 1, -1)), want,
                               rtol=1e-5)
