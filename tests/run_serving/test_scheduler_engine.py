"""Continuous-batching scheduler + engine (ISSUE 20 acceptance):
token equality against the reference generate() path, the static-
shape retrace guard, >= 3 requests genuinely in flight together,
measurably higher tokens/s than the sequential baseline on the same
seeded trace, the fp8 weight mode, and the serving/* metric family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import observability as obs
from apex_tpu.models import generate as gen
from apex_tpu.models import llama
from apex_tpu.serving import (
    ServingEngine,
    make_trace,
    pages_per_request,
)
from apex_tpu.serving.loadgen import run_closed_loop, run_sequential


@pytest.fixture(scope="module")
def model():
    cfg = llama.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _engine(params, cfg, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 3)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_prompt_len", 24)
    kw.setdefault("max_new_cap", 16)
    kw.setdefault("registry", obs.MetricRegistry())
    return ServingEngine(params, cfg, **kw)


def _reference_tokens(params, cfg, prompt, max_new):
    out = gen.generate(params, jnp.asarray(prompt)[None, :], cfg,
                       max_new)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


def test_tokens_match_generate_across_batch_compositions(model):
    """Every request's greedy tokens must be bit-identical to the
    single-request generate() path, whatever batch it shared slots
    with — per-row attention over its own block table makes request
    rows independent."""
    params, cfg = model
    rng = np.random.default_rng(0)
    jobs = [(rng.integers(0, cfg.vocab_size, size=p).astype(np.int32),
             max_new)
            for p, max_new in ((3, 4), (8, 7), (11, 4), (5, 7), (8, 4))]
    engine = _engine(params, cfg)
    for prompt, max_new in jobs:
        engine.submit(prompt, max_new)
    results = engine.run()
    assert len(results) == len(jobs)
    for rid, (prompt, max_new) in enumerate(jobs):
        want = _reference_tokens(params, cfg, prompt, max_new)
        assert results[rid]["tokens"] == want, (
            f"request {rid} diverged from generate()")


def test_decode_compiles_once_and_overlaps_requests(model):
    """The static-shape contract: one decode compile for the whole
    run (retrace guard clean), and >= 3 requests active in the same
    decode step (continuous batching, not serialization)."""
    params, cfg = model
    engine = _engine(params, cfg)
    rng = np.random.default_rng(1)
    for p, max_new in ((4, 8), (6, 8), (9, 8), (4, 6)):
        engine.submit(rng.integers(0, cfg.vocab_size, size=p).astype(
            np.int32), max_new)
    max_active = 0
    while engine.pending:
        engine.step()
        max_active = max(max_active, engine.scheduler.num_active())
    assert max_active >= 3
    assert engine.scheduler.decode_retraces() == 0
    assert engine.mean_occupancy() > 0.5


def test_eviction_refill_and_eos(model):
    """Slots freed by EOS/max-new eviction are refilled from the
    queue without retracing, and an eos_id stops a request early."""
    params, cfg = model
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, cfg.vocab_size, size=6).astype(np.int32)
    ref = _reference_tokens(params, cfg, prompt, 12)
    eos = ref[3]  # force an early stop
    stop = ref.index(eos)  # first occurrence, if earlier than 3
    engine = _engine(params, cfg, max_batch=2, eos_id=eos)
    engine.submit(prompt, 12)
    # enough queued work that eviction must refill slots
    for p in (4, 7, 5):
        engine.submit(rng.integers(0, cfg.vocab_size, size=p).astype(
            np.int32), 5)
    results = engine.run()
    assert results[0]["tokens"] == ref[:stop + 1]  # stopped AT eos
    assert len(results) == 4
    assert engine.scheduler.decode_retraces() == 0


def test_closed_loop_beats_sequential_on_same_trace(model):
    """The headline acceptance: the same seeded Poisson trace (mixed
    prompt/output lengths) completes with higher tokens/s under
    continuous batching than one-request-at-a-time generate(), and
    the report carries the latency/ttft percentiles."""
    params, cfg = model
    trace = make_trace(seed=3, num_requests=6, arrival_rate_hz=500.0,
                       prompt_lens=(4, 8, 12), output_lens=(4, 8),
                       vocab_size=cfg.vocab_size)
    assert len({(len(t.prompt), t.max_new_tokens)
                for t in trace}) >= 3  # genuinely mixed lengths
    engine = _engine(params, cfg, max_batch=4, num_pages=48)
    report = run_closed_loop(engine, trace, use_wall_clock=False)
    seq = run_sequential(params, cfg, trace)
    assert report["requests"] == 6
    assert report["decode_retraces"] == 0
    assert report["tokens_per_s"] > seq["tokens_per_s"], (
        f"continuous batching {report['tokens_per_s']} tok/s did not "
        f"beat sequential {seq['tokens_per_s']} tok/s")
    for key in ("latency_p50_ms", "latency_p99_ms", "ttft_p50_ms",
                "ttft_p99_ms", "mean_occupancy"):
        assert key in report
    # same trace, same greedy tokens on both paths
    for tr in trace:
        assert engine.results[tr.rid]["tokens"] == seq["results"][tr.rid]


def test_fp8_weight_mode_runs_clean(model):
    """weight_mode='fp8' (static per-layer scales through matmul_fp8)
    completes the trace with the retrace guard armed; tokens may
    differ from native numerics but every request must finish."""
    params, cfg = model
    engine = _engine(params, cfg, weight_mode="fp8")
    rng = np.random.default_rng(4)
    for p in (4, 9):
        engine.submit(rng.integers(0, cfg.vocab_size, size=p).astype(
            np.int32), 5)
    results = engine.run()
    assert sorted(results) == [0, 1]
    assert all(len(r["tokens"]) == 5 for r in results.values())


def test_weight_mode_validation(model):
    params, cfg = model
    with pytest.raises(ValueError, match="weight_mode"):
        _engine(params, cfg, weight_mode="int3")


def test_submit_bounds_are_loud(model):
    params, cfg = model
    engine = _engine(params, cfg, max_prompt_len=8, max_new_cap=4)
    with pytest.raises(ValueError, match="prompt length"):
        engine.submit(np.zeros(9, np.int32), 2)
    with pytest.raises(ValueError, match="max_new"):
        engine.submit(np.zeros(4, np.int32), 5)


def test_admission_respects_page_budget(model):
    """A request is only admitted when its worst-case page need fits
    the free list — no mid-decode OOM by construction."""
    params, cfg = model
    need = pages_per_request(8, 8, 8)
    engine = _engine(params, cfg, max_batch=4, num_pages=need,
                     max_prompt_len=8, max_new_cap=8)
    rng = np.random.default_rng(5)
    for _ in range(3):
        engine.submit(rng.integers(0, cfg.vocab_size, size=8).astype(
            np.int32), 8)
    max_active = 0
    while engine.pending:
        engine.step()
        max_active = max(max_active, engine.scheduler.num_active())
    assert max_active == 1  # budget of one request => one at a time
    assert len(engine.results) == 3


def test_serving_metric_family_lands_in_registry(model):
    params, cfg = model
    reg = obs.MetricRegistry()
    engine = _engine(params, cfg, registry=reg)
    trace = make_trace(seed=6, num_requests=3, arrival_rate_hz=500.0,
                       prompt_lens=(4, 8), output_lens=(4,),
                       vocab_size=cfg.vocab_size)
    run_closed_loop(engine, trace, use_wall_clock=False)
    names = {r["name"]: r for r in reg.to_records()}
    assert names["serving/requests_submitted"]["value"] == 3
    assert names["serving/requests_completed"]["value"] == 3
    assert names["serving/tokens_generated"]["value"] == 12
    assert names["serving/request_latency_ms"]["count"] == 3
    assert names["serving/ttft_ms"]["count"] == 3
    for gauge in ("serving/batch_occupancy", "serving/page_utilization",
                  "serving/latency_p99_ms", "serving/tokens_per_s",
                  "serving/mean_occupancy"):
        assert gauge in names, f"missing {gauge}"


def test_serving_targets_registered_with_own_engine_bucket():
    """Satellite: the serving decode step rides the analysis
    registries (state/memory/spmd families) and bills its wall time
    to a dedicated 'serving' bucket in the lint gate."""
    from apex_tpu.analysis import cli, targets

    assert set(targets.SERVING_TARGETS) <= set(targets.STATE_TARGETS) \
        | set(targets.MEMORY_TARGETS) | set(targets.SPMD_TARGETS)
    assert "serving" in cli.ENGINE_NAMES
    for name in targets.SERVING_TARGETS:
        assert cli.target_engine(name) == "serving"
    assert cli.target_engine("state_llama_o4_step") == "state"
