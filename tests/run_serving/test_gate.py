"""tools/metrics_report.py serving gates (ISSUE 20 satellite): the
--compare gate fails p99-latency growth and tokens/s drops past
threshold on the serving/* summary gauges, and the report renders
the serving family table from a metrics dump."""

import json
import os
import subprocess
import sys

_TOOL = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "tools", "metrics_report.py")


def _dump(path, p99=100.0, tps=50.0, extra=()):
    records = [
        {"type": "gauge", "name": "serving/latency_p99_ms",
         "value": p99},
        {"type": "gauge", "name": "serving/tokens_per_s", "value": tps},
        {"type": "gauge", "name": "serving/latency_p50_ms",
         "value": p99 / 2},
        {"type": "gauge", "name": "serving/mean_occupancy",
         "value": 0.8},
        *extra,
    ]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _run(*args):
    return subprocess.run([sys.executable, _TOOL, *args],
                          capture_output=True, text=True, timeout=240)


def test_within_threshold_passes(tmp_path):
    base = _dump(tmp_path / "base.jsonl", p99=100.0, tps=50.0)
    cur = _dump(tmp_path / "cur.jsonl", p99=105.0, tps=48.0)
    proc = _run(cur, "--compare", base)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 regression(s)" in proc.stdout


def test_p99_latency_growth_fails(tmp_path):
    base = _dump(tmp_path / "base.jsonl", p99=100.0, tps=50.0)
    cur = _dump(tmp_path / "cur.jsonl", p99=140.0, tps=50.0)
    proc = _run(cur, "--compare", base)
    assert proc.returncode == 1
    assert "REGRESSION serving/latency_p99_ms" in proc.stdout
    # a looser threshold lets the same diff pass
    assert _run(cur, "--compare", base,
                "--compare-threshold", "0.5").returncode == 0


def test_tokens_per_s_drop_fails(tmp_path):
    base = _dump(tmp_path / "base.jsonl", p99=100.0, tps=50.0)
    cur = _dump(tmp_path / "cur.jsonl", p99=100.0, tps=35.0)
    proc = _run(cur, "--compare", base)
    assert proc.returncode == 1
    assert "REGRESSION serving/tokens_per_s" in proc.stdout
    assert _run(cur, "--compare", base,
                "--compare-threshold", "0.5").returncode == 0


def test_faster_and_leaner_passes(tmp_path):
    """Improvement in both gated directions is never a regression."""
    base = _dump(tmp_path / "base.jsonl", p99=100.0, tps=50.0)
    cur = _dump(tmp_path / "cur.jsonl", p99=60.0, tps=80.0)
    assert _run(cur, "--compare", base).returncode == 0


def test_gauge_only_in_base_is_info_not_failure(tmp_path):
    base = _dump(tmp_path / "base.jsonl")
    cur = tmp_path / "cur.jsonl"
    with open(cur, "w") as f:
        f.write(json.dumps({"type": "gauge", "name": "other/x",
                            "value": 1.0}) + "\n")
    proc = _run(str(cur), "--compare", base)
    assert proc.returncode == 0
    assert "only in base" in proc.stdout


def test_report_renders_serving_family(tmp_path):
    dump = _dump(tmp_path / "run.jsonl", extra=[
        {"type": "counter", "name": "serving/requests_completed",
         "value": 8},
        {"type": "counter", "name": "serving/tokens_generated",
         "value": 56},
        {"type": "histogram", "name": "serving/request_latency_ms",
         "count": 8, "total": 800.0, "min": 50.0, "max": 200.0,
         "mean": 100.0, "p50": 90.0, "p90": 150.0, "p99": 190.0},
    ])
    proc = _run(dump)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "serving/* family" in out
    assert "completed 8" in out
    assert "tokens generated 56" in out
    assert "request_latency_ms" in out
    # --json mode carries the family as a machine-readable object
    jproc = _run(dump, "--json")
    assert jproc.returncode == 0
    fams = [json.loads(line) for line in jproc.stdout.splitlines()
            if "serving_family" in line]
    assert fams
    assert fams[0]["serving_family"]["counters"][
        "requests_completed"] == 8
