"""Serving preemption chaos (ISSUE 20): a seeded fault plan preempts
the engine mid-decode — it must stop admitting, drain, emergency-dump
queue + KV pages, raise Preempted (exit code 75), and a resumed
engine must complete every request with BIT-identical tokens to an
uninterrupted run (greedy decode + scatter-restored pages)."""

import json
import os

import jax
import numpy as np
import pytest

from apex_tpu import observability as obs
from apex_tpu.models import llama
from apex_tpu.resilience.faults import FaultPlan
from apex_tpu.resilience.loop import Preempted
from apex_tpu.resilience.preemption import EXIT_PREEMPTED
from apex_tpu.serving import ServingEngine
from apex_tpu.serving.engine import (
    _PAGES_FILE,
    _STATE_FILE,
    DUMP_SCHEMA_VERSION,
)


@pytest.fixture(scope="module")
def model():
    cfg = llama.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _engine(params, cfg, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch", 2)
    kw.setdefault("num_pages", 32)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_cap", 16)
    kw.setdefault("registry", obs.MetricRegistry())
    return ServingEngine(params, cfg, **kw)


def _jobs(cfg, n=6, seed=7):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, cfg.vocab_size,
                          size=int(rng.integers(3, 12))).astype(np.int32),
             int(rng.integers(4, 9))) for _ in range(n)]


def _submit_all(engine, jobs):
    for prompt, max_new in jobs:
        engine.submit(prompt, max_new)


def test_preempt_drain_dump_resume_bit_identical(model, tmp_path):
    params, cfg = model
    jobs = _jobs(cfg)

    # the uninterrupted twin defines the expected tokens
    twin = _engine(params, cfg)
    _submit_all(twin, jobs)
    want = twin.run()

    d = str(tmp_path / "dump")
    plan = FaultPlan.parse("seed=1,preempt@4")
    engine = _engine(params, cfg, fault_plan=plan, dump_dir=d)
    _submit_all(engine, jobs)
    with pytest.raises(Preempted) as exc:
        engine.run()
    assert exc.value.exit_code == EXIT_PREEMPTED == 75
    assert engine.draining
    with pytest.raises(RuntimeError, match="draining"):
        engine.submit(jobs[0][0], 4)

    # the dump is complete: state.json (the completeness marker) +
    # one k/v pair per in-flight request
    state_path = os.path.join(d, _STATE_FILE)
    with open(state_path) as f:
        state = json.load(f)
    assert state["schema_version"] == DUMP_SCHEMA_VERSION
    assert state["reason"].startswith("fault-plan preempt")
    inflight = state["inflight"]
    assert inflight, "preempt@4 must catch requests mid-decode"
    with np.load(os.path.join(d, _PAGES_FILE)) as pages:
        for rec in inflight:
            assert f"k_{rec['rid']}" in pages
            assert f"v_{rec['rid']}" in pages
            assert rec["tokens"], "mid-decode request has tokens"
    # every request is either completed, in flight, or still queued
    accounted = (set(int(r) for r in state["completed"])
                 | {r["rid"] for r in inflight}
                 | {r["rid"] for r in state["queued"]})
    assert accounted == set(range(len(jobs)))

    # resume: same geometry from the dump, KV pages restored by
    # scatter — remaining tokens must be bit-identical to the twin
    resumed = ServingEngine.resume(d, params, cfg,
                                   registry=obs.MetricRegistry())
    got = resumed.run()
    assert got == want
    assert resumed.scheduler.decode_retraces() == 0


def test_exit_on_preempt_exits_75(model, tmp_path):
    """Process-supervisor contract: exit_on_preempt=True turns the
    drain into sys.exit(75) instead of raising."""
    params, cfg = model
    engine = _engine(params, cfg, fault_plan=FaultPlan.parse(
        "seed=1,preempt@2"), dump_dir=str(tmp_path / "d"),
        exit_on_preempt=True)
    _submit_all(engine, _jobs(cfg, n=3))
    with pytest.raises(SystemExit) as exc:
        engine.run()
    assert exc.value.code == 75
    assert os.path.exists(str(tmp_path / "d" / _STATE_FILE))


def test_drain_publishes_preemption_telemetry(model, tmp_path):
    params, cfg = model
    reg = obs.MetricRegistry()
    engine = _engine(params, cfg, registry=reg,
                     fault_plan=FaultPlan.parse("seed=1,preempt@3"),
                     dump_dir=str(tmp_path / "d"))
    _submit_all(engine, _jobs(cfg, n=4))
    with pytest.raises(Preempted):
        engine.run()
    records = reg.to_records()
    names = {r["name"]: r for r in records if "name" in r}
    assert names["serving/requests_preempted"]["value"] >= 1
    events = [r for r in records if r.get("type") == "event"
              and r.get("name") == "serving_drain"]
    assert events
    assert events[0]["fields"]["iteration"] == engine.iteration


def test_resume_rejects_schema_drift(model, tmp_path):
    params, cfg = model
    d = str(tmp_path / "d")
    engine = _engine(params, cfg, fault_plan=FaultPlan.parse(
        "seed=1,preempt@2"), dump_dir=d)
    _submit_all(engine, _jobs(cfg, n=3))
    with pytest.raises(Preempted):
        engine.run()
    state_path = os.path.join(d, _STATE_FILE)
    with open(state_path) as f:
        state = json.load(f)
    state["schema_version"] = 999
    with open(state_path, "w") as f:
        json.dump(state, f)
    with pytest.raises(ValueError, match="schema_version"):
        ServingEngine.resume(d, params, cfg,
                             registry=obs.MetricRegistry())


def test_fault_plan_does_not_refire_on_resume(model, tmp_path):
    """should_fire spends the event: passing the SAME plan instance to
    the resumed engine must not re-preempt at the same iteration."""
    params, cfg = model
    d = str(tmp_path / "d")
    plan = FaultPlan.parse("seed=1,preempt@3")
    engine = _engine(params, cfg, fault_plan=plan, dump_dir=d)
    _submit_all(engine, _jobs(cfg, n=4))
    with pytest.raises(Preempted):
        engine.run()
    resumed = ServingEngine.resume(d, params, cfg, fault_plan=plan,
                                   registry=obs.MetricRegistry())
    results = resumed.run()  # completes — the spent plan stays quiet
    assert len(results) == 4
