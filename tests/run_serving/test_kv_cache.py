"""Paged KV cache (apex_tpu/serving/kv_cache.py): allocator
accounting, the calibrated page-budget derivation, and the
write/gather/restore/defrag data paths the scheduler and the
emergency dump depend on (ISSUE 20)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import llama
from apex_tpu.serving import kv_cache as kvc


def _cfg():
    return llama.tiny()


# ---------------------------------------------------------- allocator


def test_allocator_alloc_free_accounting():
    a = kvc.PageAllocator(6)
    assert a.num_free == 6 and a.num_used == 0
    p1 = a.alloc(2, owner="r1")
    p2 = a.alloc(3, owner="r2")
    assert sorted(p1 + p2) == [0, 1, 2, 3, 4]
    assert a.num_free == 1 and a.num_used == 5
    assert a.pages_of("r1") == p1
    assert a.can_alloc(1) and not a.can_alloc(2)
    assert a.free_owner("r1") == 2
    assert a.num_free == 3
    assert a.pages_of("r1") == []
    # freed pages are reusable and accounting stays exact
    p3 = a.alloc(3, owner="r3")
    assert a.num_free == 0
    assert sorted(a.live_pages()) == sorted(p2 + p3)


def test_allocator_exhaustion_is_loud():
    a = kvc.PageAllocator(2)
    a.alloc(2, owner="r1")
    with pytest.raises(RuntimeError, match="out of KV pages"):
        a.alloc(1, owner="r2")
    with pytest.raises(ValueError):
        a.alloc(0, owner="r3")
    with pytest.raises(ValueError):
        kvc.PageAllocator(0)


# ------------------------------------------------------------- budget


def test_page_hbm_bytes_formula():
    cfg = _cfg()
    got = kvc.page_hbm_bytes(cfg, page_size=8)
    want = (2 * cfg.num_layers * 8 * cfg.num_kv_heads * cfg.head_dim
            * jnp.dtype(cfg.dtype).itemsize)
    assert got == want


def test_derive_page_budget_math_with_overrides():
    cfg = _cfg()
    page_bytes = kvc.page_hbm_bytes(cfg, page_size=8)
    priors = {"priors": {"serving_decode_step": {"ratio": 2.0}},
              "default_ratio": 1.5}
    b = kvc.derive_page_budget(cfg, 8, hbm_bytes=page_bytes * 100,
                               watermark_bytes=page_bytes * 10,
                               priors=priors, safety=0.5)
    # usable = 100p * 0.5 - 10p = 40p; effective page cost = 2.0p
    assert b.usable_bytes == page_bytes * 40
    assert b.ratio == 2.0
    assert b.pages == 20
    assert b.page_bytes == page_bytes
    # no serving-specific prior -> the document default prices the page
    b2 = kvc.derive_page_budget(cfg, 8, hbm_bytes=page_bytes * 100,
                                watermark_bytes=0,
                                priors={"priors": {},
                                        "default_ratio": 1.5},
                                safety=1.0)
    assert b2.ratio == 1.5
    assert b2.pages == int(page_bytes * 100
                           // int(np.ceil(page_bytes * 1.5)))


def test_derive_page_budget_watermark_floor_and_safety_validation():
    cfg = _cfg()
    page_bytes = kvc.page_hbm_bytes(cfg, page_size=8)
    b = kvc.derive_page_budget(
        cfg, 8, hbm_bytes=page_bytes * 4,
        watermark_bytes=page_bytes * 50,
        priors={"priors": {}, "default_ratio": 1.0})
    assert b.usable_bytes == 0 and b.pages == 0
    with pytest.raises(ValueError, match="safety"):
        kvc.derive_page_budget(cfg, 8, hbm_bytes=1, watermark_bytes=0,
                               priors={"priors": {},
                                       "default_ratio": 1.0},
                               safety=1.5)


def test_derive_page_budget_live_tier_defaults():
    """With no overrides, the budget reads the real memory tier
    (device_hbm_bytes + committed priors) and lands a positive page
    count for the tiny config on any host."""
    b = kvc.derive_page_budget(_cfg(), 8)
    assert b.pages > 0
    assert b.ratio > 0
    assert b.hbm_bytes > b.page_bytes


# --------------------------------------------------------- data paths


def _fill(cache, pages, seed):
    """write_prompt a recognizable pattern; returns the [L,S,nkv,d]
    host arrays written."""
    cfg = cache.cfg
    s = len(pages) * cache.page_size
    rng = np.random.default_rng(seed)
    ks = rng.standard_normal(
        (cfg.num_layers, s, cfg.num_kv_heads, cfg.head_dim)).astype(
        np.float32)
    vs = rng.standard_normal(ks.shape).astype(np.float32)
    cache.write_prompt(pages, jnp.asarray(ks), jnp.asarray(vs))
    return ks, vs


def test_write_gather_restore_roundtrip():
    cfg = _cfg()
    cache = kvc.PagedKVCache(cfg, num_pages=6, page_size=4)
    pages = cache.alloc.alloc(2, owner=0)
    ks, vs = _fill(cache, pages, seed=0)
    k, v = cache.gather_pages(pages)
    assert k.shape == (cfg.num_layers, 2, 4, cfg.num_kv_heads,
                       cfg.head_dim)
    np.testing.assert_array_equal(
        k.reshape(cfg.num_layers, 8, cfg.num_kv_heads, cfg.head_dim), ks)
    # wipe + restore must be bit-exact (the resume contract)
    cache.k_pages = jnp.zeros_like(cache.k_pages)
    cache.v_pages = jnp.zeros_like(cache.v_pages)
    cache.restore_pages(pages, k, v)
    k2, v2 = cache.gather_pages(pages)
    np.testing.assert_array_equal(k2, k)
    np.testing.assert_array_equal(v2, v)


def test_write_prompt_length_mismatch_is_loud():
    cache = kvc.PagedKVCache(_cfg(), num_pages=4, page_size=4)
    pages = cache.alloc.alloc(1, owner=0)
    cfg = cache.cfg
    bad = jnp.zeros((cfg.num_layers, 6, cfg.num_kv_heads, cfg.head_dim))
    with pytest.raises(ValueError, match="prefill length"):
        cache.write_prompt(pages, bad, bad)


def test_trash_page_never_allocated():
    cache = kvc.PagedKVCache(_cfg(), num_pages=3, page_size=4)
    got = cache.alloc.alloc(3, owner=0)
    assert cache.trash_page == 3
    assert cache.trash_page not in got
    assert cache.k_pages.shape[1] == 4  # 3 real + 1 trash


def test_defrag_compacts_and_moves_data():
    cache = kvc.PagedKVCache(_cfg(), num_pages=8, page_size=4)
    a = cache.alloc
    a.alloc(2, owner="a")        # pages 0,1
    a.alloc(2, owner="b")        # pages 2,3
    a.alloc(2, owner="c")        # pages 4,5
    kb, vb = _fill(cache, a.pages_of("b"), seed=1)
    kc, vc = _fill(cache, a.pages_of("c"), seed=2)
    a.free_owner("a")
    a.free_owner("b")
    mapping = cache.defrag()
    # live pages 4,5 move to the front
    assert mapping == {4: 0, 5: 1}
    assert a.pages_of("c") == [0, 1]
    assert a.num_used == 2 and a.num_free == 6
    # the data followed its pages
    k, _ = cache.gather_pages(a.pages_of("c"))
    np.testing.assert_array_equal(
        k.reshape(kc.shape[0], -1, *kc.shape[2:]), kc)
    # already-compact cache is a no-op
    assert cache.defrag() == {}


def test_utilization_tracks_allocator():
    cache = kvc.PagedKVCache(_cfg(), num_pages=4, page_size=4)
    assert cache.utilization() == 0.0
    cache.alloc.alloc(1, owner=0)
    assert cache.utilization() == 0.25
    cache.alloc.free_owner(0)
    assert cache.utilization() == 0.0
