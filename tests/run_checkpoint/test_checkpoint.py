"""Checkpoint/resume (SURVEY §5; ref amp state_dict + Megatron
save/load): params + optimizer state + amp automaton must round-trip, and
CheckpointManager must retain only max_to_keep newest steps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.checkpoint import (
    CheckpointManager,
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from apex_tpu.optimizers import fused_adam


def _train_state():
    params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros((3,))}
    tx = fused_adam(lr=1e-2)
    opt_state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    return params, opt_state, tx


def test_save_restore_roundtrip(tmp_path):
    params, opt_state, _ = _train_state()
    state = {"params": params, "opt": opt_state}
    save_checkpoint(str(tmp_path / "ckpt"), state, step=3)
    assert latest_step(str(tmp_path / "ckpt")) == 3
    got = restore_checkpoint(str(tmp_path / "ckpt"), target=state)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_restore_resumes_training_identically(tmp_path):
    """Training N steps == training k, checkpoint, restore, train N-k."""
    params = {"w": jnp.ones((4, 4))}
    tx = fused_adam(lr=1e-2)

    def steps(params, opt_state, n, seed0):
        for i in range(n):
            g = {"w": jax.random.normal(jax.random.PRNGKey(seed0 + i),
                                        (4, 4))}
            u, opt_state = tx.update(g, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, q: p + q, params, u)
        return params, opt_state

    full, _ = steps(params, tx.init(params), 6, 0)

    p3, s3 = steps(params, tx.init(params), 3, 0)
    save_checkpoint(str(tmp_path / "c"), {"p": p3, "o": s3}, step=3)
    got = restore_checkpoint(str(tmp_path / "c"), target={"p": p3, "o": s3})
    resumed, _ = steps(got["p"], got["o"], 3, 3)
    np.testing.assert_allclose(np.asarray(resumed["w"]),
                               np.asarray(full["w"]), rtol=1e-6)


def test_amp_state_roundtrips_through_checkpoint(tmp_path):
    params = {"w": jnp.ones((2, 2))}
    _, handle = amp.initialize(params, opt_level="O2", verbosity=0)
    sstate = handle.scaler_state
    # advance the automaton: one overflow halves the scale
    sstate = handle.scaler.update(sstate, jnp.asarray(True))
    save_checkpoint(str(tmp_path / "c"), {"amp": sstate}, step=0)
    got = restore_checkpoint(str(tmp_path / "c"), target={"amp": sstate})
    assert float(got["amp"].loss_scale) == float(sstate.loss_scale)
    assert int(got["amp"].overflows) == 1


def test_manager_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
    for step in (1, 2, 3, 4):
        mgr.save(step, {"x": jnp.asarray(float(step))})
    assert mgr.latest_step() == 4
    got = mgr.restore(target={"x": jnp.asarray(0.0)})
    assert float(got["x"]) == 4.0
    # only the 2 newest survive
    got3 = mgr.restore(target={"x": jnp.asarray(0.0)}, step=3)
    assert float(got3["x"]) == 3.0
    with pytest.raises(Exception):
        mgr.restore(target={"x": jnp.asarray(0.0)}, step=1)


def test_master_params_track_model_params(tmp_path):
    """ref tests/distributed/amp_master_params: after O2 steps the bf16
    model params equal the fp32 masters within cast tolerance."""
    params32 = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 8))}
    cast_params, handle = amp.initialize(params32, opt_level="O2",
                                         verbosity=0)
    policy, scaler = handle.policy, handle.scaler
    sstate = handle.scaler_state
    tx = fused_adam(lr=1e-2)
    opt_state = tx.init(params32)

    master = params32
    for i in range(3):
        g = jax.tree_util.tree_map(
            lambda p: 0.1 * jax.random.normal(jax.random.PRNGKey(i),
                                              p.shape), master)
        updates, opt_state, sstate, _ = amp.scaled_update(
            tx, scaler, g, opt_state, master, sstate)
        master = jax.tree_util.tree_map(lambda p, u: p + u, master, updates)
        model = policy.cast_model(master)  # bf16 view

    assert jax.tree_util.tree_leaves(model)[0].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(model["w"], np.float32), np.asarray(master["w"]),
        atol=4e-3)  # bf16 quantization of fp32 masters


def test_async_writer_roundtrip(tmp_path):
    from apex_tpu.checkpoint import AsyncCheckpointWriter, restore_checkpoint

    state = {"w": jax.random.normal(jax.random.PRNGKey(0), (32, 16)),
             "step": jnp.asarray(7)}
    w = AsyncCheckpointWriter()
    p = w.save(str(tmp_path / "ck"), state, step=7)
    # training continues while the write is in flight
    busy = (state["w"] @ state["w"].T).sum()
    w.wait()
    got = restore_checkpoint(str(tmp_path / "ck"), target=state, step=7)
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.asarray(state["w"]))
    assert int(got["step"]) == 7
    w.close()
    del busy, p


def test_manager_async_save_retention(tmp_path):
    from apex_tpu.checkpoint import CheckpointManager

    m = CheckpointManager(str(tmp_path), max_to_keep=2, async_save=True)
    state = {"x": jnp.arange(8.0)}
    for s in (1, 2, 3):
        m.save(s, {"x": state["x"] + s})
    m.wait_until_finished()
    assert m.latest_step() == 3
    got = m.restore(target=state)
    np.testing.assert_allclose(np.asarray(got["x"]),
                               np.asarray(state["x"] + 3))
    # retention applied after the writes landed
    import os as _os

    kept = sorted(d for d in _os.listdir(tmp_path)
                  if d.startswith("step_"))
    assert len(kept) == 2


def test_gc_survives_orbax_tmp_dirs(tmp_path):
    from apex_tpu.checkpoint import CheckpointManager

    m = CheckpointManager(str(tmp_path), max_to_keep=1)
    # a crash can leave an orbax in-flight temp dir behind
    import os as _os

    _os.makedirs(tmp_path / "step_00000001.orbax-checkpoint-tmp-99")
    for s in (1, 2):
        m.save(s, {"x": jnp.arange(4.0)})
    assert m.latest_step() == 2


def test_async_writer_concurrent_save_and_wait_threads(tmp_path):
    """Regression (ISSUE 16): save/wait/close serialize through the
    writer's RLock — a trainer thread saving while another thread
    fences must keep the single-write-in-flight contract, commit every
    step exactly once, and leave no torn .tmp dirs."""
    import os as _os
    import threading

    from apex_tpu.checkpoint import (
        AsyncCheckpointWriter,
        latest_valid_step,
        restore_checkpoint,
    )

    w = AsyncCheckpointWriter()
    steps = (1, 2, 3, 4)
    errors = []
    stop = threading.Event()

    def fencer():
        # an eval thread draining the in-flight write in a loop,
        # interleaving with the trainer's save() fences
        try:
            while not stop.is_set():
                w.wait()
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    ft = threading.Thread(target=fencer, daemon=True)
    ft.start()
    try:
        for s in steps:
            w.save(str(tmp_path), {"x": jnp.full((4,), float(s))},
                   step=s)
    finally:
        stop.set()
        ft.join(timeout=30)
    assert not ft.is_alive() and not errors
    w.close()

    assert latest_valid_step(str(tmp_path)) == steps[-1]
    assert not [d for d in _os.listdir(tmp_path) if d.endswith(".tmp")]
    for s in steps:
        got = restore_checkpoint(str(tmp_path),
                                 target={"x": jnp.zeros((4,))}, step=s)
        np.testing.assert_array_equal(np.asarray(got["x"]),
                                      np.full((4,), float(s)))
