"""Commit-marker format 1 <-> format 2 compatibility (ISSUE 18).

Format 2 adds the semantic ``state_schema`` block (treedef + per-leaf
path/shape/dtype/spec/kind + fingerprint) to ``_APEX_COMMIT.json``.
Both directions must keep working: a format-1 checkpoint (pre-schema)
validates, restores, and GCs under the current code; a format-2
checkpoint validates under format-1-era semantics (the validator reads
only the ``files`` manifest) — and the schema the saver writes is
bit-identical to what the state engine derives from code, so the
drift check compares real encodings.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.checkpoint import (
    COMMIT_MARKER,
    encode_spec,
    gc_partial_checkpoints,
    latest_valid_step,
    manifest_state_schema,
    read_manifest,
    restore_checkpoint,
    save_checkpoint,
    schema_fingerprint,
    state_schema_of,
    validate_step_dir,
    write_commit_marker,
)

_STATE = {"w": jnp.arange(6.0, dtype=jnp.float32).reshape(2, 3),
          "count": jnp.asarray(3, jnp.int32)}


def _write_format1_checkpoint(root, step=1):
    """A pre-schema checkpoint: real orbax payload, then a marker
    written WITHOUT a schema — byte-compatible with every release
    before format 2."""
    save_checkpoint(str(root), _STATE, step=step)
    d = os.path.join(str(root), f"step_{step:08d}")
    marker = os.path.join(d, COMMIT_MARKER)
    os.remove(marker)
    write_commit_marker(d, step=step)  # no state_schema -> format 1
    return d


# ------------------------------------------------- format 1 under today


def test_format1_dir_still_validates_restores_and_gcs(tmp_path):
    d = _write_format1_checkpoint(tmp_path, step=1)
    payload = read_manifest(d)
    assert payload["format"] == 1
    assert "state_schema" not in payload
    assert validate_step_dir(d, deep=True)
    assert latest_valid_step(str(tmp_path)) == 1
    got = restore_checkpoint(str(tmp_path), target=_STATE, step=1)
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.asarray(_STATE["w"]))
    # GC sees it as committed, not a torn leftover
    assert gc_partial_checkpoints(str(tmp_path)) == []
    assert os.path.isdir(d)


def test_format1_schema_lookup_returns_none(tmp_path):
    d = _write_format1_checkpoint(tmp_path, step=2)
    assert manifest_state_schema(d) is None


def test_format1_manifest_passes_state_engine_backcompat(tmp_path):
    """The engine's drift check treats a schemaless dir as nothing to
    compare — a fleet of old checkpoints never turns red on upgrade."""
    from apex_tpu.analysis.state_checks import analyze_state

    d = _write_format1_checkpoint(tmp_path, step=3)

    def step(s, g):
        return {"w": s["w"] - g, "count": s["count"] + 1}

    assert analyze_state(step, _STATE, jnp.ones((2, 3)),
                         name="fmt1_roundtrip", manifest=d) == []


# ------------------------------------------------- format 2 both ways


def test_save_checkpoint_writes_format2_schema(tmp_path):
    save_checkpoint(str(tmp_path), _STATE, step=5)
    d = os.path.join(str(tmp_path), "step_00000005")
    payload = read_manifest(d)
    assert payload["format"] == 2
    schema = payload["state_schema"]
    assert schema == manifest_state_schema(d)
    assert schema["fingerprint"] == schema_fingerprint(schema)
    by_path = {lf["path"]: lf for lf in schema["leaves"]}
    assert by_path["['w']"]["shape"] == [2, 3]
    assert by_path["['w']"]["dtype"] == "float32"
    assert by_path["['count']"]["dtype"] == "int32"


def test_format2_dir_validates_under_format1_semantics(tmp_path):
    """A format-1-era reader checks only the ``files`` manifest — the
    schema block must ride along without breaking that contract."""
    save_checkpoint(str(tmp_path), _STATE, step=6)
    d = os.path.join(str(tmp_path), "step_00000006")
    payload = read_manifest(d)
    # the format-1 subset is intact and sufficient on its own
    files = payload["files"]
    assert files and all(
        os.path.getsize(os.path.join(d, rel)) == meta["size"]
        for rel, meta in files.items())
    assert validate_step_dir(d, deep=True)
    got = restore_checkpoint(str(tmp_path), target=_STATE, step=6)
    np.testing.assert_array_equal(np.asarray(got["count"]),
                                  np.asarray(_STATE["count"]))


def test_format2_schema_matches_engine_derivation(tmp_path):
    """The design invariant the drift check rests on: the saver's
    encoding (checkpoint.state_schema_of) and the engine's code-derived
    encoding agree to the fingerprint."""
    from apex_tpu.analysis.state_checks import derive_state_schema

    save_checkpoint(str(tmp_path), _STATE, step=7)
    disk = manifest_state_schema(
        os.path.join(str(tmp_path), "step_00000007"))

    def step(s, g):
        return {"w": s["w"] - g, "count": s["count"] + 1}

    code = derive_state_schema(step, _STATE,
                               jnp.ones((2, 3))).to_manifest()
    assert code["treedef"] == disk["treedef"]
    assert code["leaves"] == disk["leaves"]
    assert code["fingerprint"] == disk["fingerprint"]


def test_format2_drift_detected_after_state_evolves(tmp_path):
    """Round-trip the other direction: a format-2 checkpoint written
    for YESTERDAY'S state turns red when the code's state grows a
    field — exactly the upgrade hazard the block exists to catch."""
    from apex_tpu.analysis.state_checks import analyze_state

    save_checkpoint(str(tmp_path), _STATE, step=8)
    d = os.path.join(str(tmp_path), "step_00000008")
    new_state = dict(_STATE, ring=jnp.zeros((4,), jnp.float32))

    def step(s, g):
        return {"w": s["w"] - g, "count": s["count"] + 1,
                "ring": s["ring"]}

    found = analyze_state(step, new_state, jnp.ones((2, 3)),
                          name="evolved", manifest=d,
                          checks=("ckpt-schema-drift",))
    assert found
    assert any("ring" in f.message for f in found)


def test_async_writer_commits_format2(tmp_path):
    from apex_tpu.checkpoint import AsyncCheckpointWriter

    w = AsyncCheckpointWriter()
    w.save(str(tmp_path), _STATE, step=9)
    w.wait()
    w.close()
    d = os.path.join(str(tmp_path), "step_00000009")
    schema = manifest_state_schema(d)
    assert schema is not None
    assert schema["fingerprint"] == state_schema_of(
        _STATE)["fingerprint"]


# ----------------------------------------------- schema encoding units


def test_fingerprint_stable_and_sensitive():
    a = state_schema_of(_STATE)
    b = state_schema_of(jax.tree_util.tree_map(jnp.copy, _STATE))
    assert a["fingerprint"] == b["fingerprint"]
    narrowed = state_schema_of(
        {"w": _STATE["w"].astype(jnp.bfloat16), "count": _STATE["count"]})
    assert narrowed["fingerprint"] != a["fingerprint"]


def test_schema_is_json_native():
    schema = state_schema_of(_STATE)
    assert json.loads(json.dumps(schema)) == schema


def test_encode_spec_forms():
    from jax.sharding import PartitionSpec as P

    assert encode_spec(None) is None
    assert encode_spec(P()) == []
    assert encode_spec(P("dp", None)) == ["dp", None]
    assert encode_spec(P(("dp", "tp"), None)) == [["dp", "tp"], None]


def test_state_schema_of_specs_mismatch_loud():
    from jax.sharding import PartitionSpec as P

    with pytest.raises(ValueError, match="diverged"):
        state_schema_of(_STATE, specs={"w": P()})


def test_state_schema_of_explicit_specs_encoded():
    from jax.sharding import PartitionSpec as P

    schema = state_schema_of(_STATE,
                             specs={"w": P("dp"), "count": P()})
    by_path = {lf["path"]: lf for lf in schema["leaves"]}
    assert by_path["['w']"]["spec"] == ["dp"]
    assert by_path["['count']"]["spec"] == []


def test_schema_failure_never_blocks_save(tmp_path, monkeypatch):
    """Durability beats observability: a broken schema derivation
    degrades to a format-1 marker, never a failed save."""
    import apex_tpu.checkpoint as ckpt

    monkeypatch.setattr(
        ckpt, "state_schema_of",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
    save_checkpoint(str(tmp_path), _STATE, step=10)
    d = os.path.join(str(tmp_path), "step_00000010")
    payload = read_manifest(d)
    assert payload["format"] == 1
    assert validate_step_dir(d)
    got = restore_checkpoint(str(tmp_path), target=_STATE, step=10)
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.asarray(_STATE["w"]))
