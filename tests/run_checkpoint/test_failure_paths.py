"""Async/atomic checkpoint FAILURE paths (ISSUE 5 satellite): torn
writes, ENOSPC, corrupted commits, restore-from-previous-valid-step —
the cases the old happy-path suite never exercised."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import checkpoint as ckpt
from apex_tpu.resilience import (
    DiskFull,
    FaultPlan,
    Policy,
    TornWrite,
    inject_checkpoint_failures,
)
from apex_tpu.observability import MetricRegistry


def _state(v: float):
    return {"w": jnp.full((4, 4), v), "step": jnp.asarray(int(v))}


def _corrupt_one_file(step_dir: str):
    """Truncate the first manifest-listed file (a post-commit bitrot /
    partial-copy scenario)."""
    with open(os.path.join(step_dir, ckpt.COMMIT_MARKER)) as f:
        manifest = json.load(f)
    rel = sorted(r for r, m in manifest["files"].items()
                 if m["size"] > 0)[0]
    with open(os.path.join(step_dir, rel), "w") as f:
        f.write("")
    return rel


def test_torn_write_leaves_only_tmp_and_is_invisible(tmp_path):
    plan = FaultPlan(steps={"ckpt_torn": {2}})
    ckpt.save_checkpoint(str(tmp_path), _state(1), step=1)
    with inject_checkpoint_failures(plan, registry=MetricRegistry()):
        with pytest.raises(TornWrite):
            ckpt.save_checkpoint(str(tmp_path), _state(2), step=2)
    # the torn write is a .tmp dir: not a committed step, never restored
    assert ckpt.latest_step(str(tmp_path)) == 1
    assert ckpt.latest_valid_step(str(tmp_path)) == 1
    leftovers = [d for d in os.listdir(tmp_path) if d.endswith(".tmp")]
    assert leftovers == ["step_00000002.tmp"]
    got = ckpt.restore_checkpoint(str(tmp_path), target=_state(0))
    assert float(np.asarray(got["w"])[0, 0]) == 1.0
    # gc removes the leftover; the valid step survives
    removed = ckpt.gc_partial_checkpoints(str(tmp_path))
    assert len(removed) == 1 and removed[0].endswith(".tmp")
    assert ckpt.latest_valid_step(str(tmp_path)) == 1


def test_enospc_injection_is_retryable(tmp_path):
    """A disk-full save fails; a retry policy rides through it (the
    fault is spent, like a real transient) and the checkpoint lands."""
    reg = MetricRegistry()
    # un-retried, the injected ENOSPC surfaces as a (retryable) OSError
    with inject_checkpoint_failures(FaultPlan(steps={"ckpt_enospc": {5}}),
                                    registry=reg):
        with pytest.raises(DiskFull) as ei:
            ckpt.save_checkpoint(str(tmp_path / "raw"), _state(5), step=5)
    assert ei.value.errno == 28  # ENOSPC
    # a fresh plan (fresh process semantics) + retry policy ride through:
    # attempt 1 hits the fault (spending it), attempt 2 lands the save
    policy = Policy(max_attempts=3, initial_backoff=0.001,
                    sleep=lambda s: None, name="ckpt", registry=reg)
    with inject_checkpoint_failures(FaultPlan(steps={"ckpt_enospc": {5}}),
                                    registry=reg):
        path = policy.call(ckpt.save_checkpoint, str(tmp_path / "ok"),
                           _state(5), step=5)
    assert ckpt.validate_step_dir(path, deep=True)
    assert reg.counter("resilience/retries", scope="ckpt").value == 1


def test_restore_falls_back_to_previous_valid_step(tmp_path):
    ckpt.save_checkpoint(str(tmp_path), _state(1), step=1)
    p2 = ckpt.save_checkpoint(str(tmp_path), _state(2), step=2)
    _corrupt_one_file(p2)
    assert not ckpt.validate_step_dir(p2)
    assert ckpt.latest_step(str(tmp_path)) == 2
    assert ckpt.latest_valid_step(str(tmp_path)) == 1
    got = ckpt.restore_checkpoint(str(tmp_path), target=_state(0))
    assert float(np.asarray(got["w"])[0, 0]) == 1.0


def test_deep_validation_catches_same_size_corruption(tmp_path):
    p = ckpt.save_checkpoint(str(tmp_path), _state(3), step=3)
    with open(os.path.join(p, ckpt.COMMIT_MARKER)) as f:
        manifest = json.load(f)
    rel, meta = max(manifest["files"].items(),
                    key=lambda kv: kv[1]["size"])
    full = os.path.join(p, rel)
    with open(full, "r+b") as f:  # flip bytes, keep the size
        f.seek(0)
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]))
    assert ckpt.validate_step_dir(p, deep=False)  # size unchanged
    assert not ckpt.validate_step_dir(p, deep=True)
    assert ckpt.latest_valid_step(str(tmp_path), deep=True) is None


def test_async_writer_raise_mid_write_keeps_previous_step(tmp_path):
    """The satellite case: an async writer that fails between data and
    commit. The failure surfaces at the fence (wait/next save), the
    torn dir stays uncommitted, and the writer keeps working."""
    plan = FaultPlan(steps={"ckpt_torn": {2}})
    w = ckpt.AsyncCheckpointWriter()
    with inject_checkpoint_failures(plan, registry=MetricRegistry()):
        w.save(str(tmp_path), _state(1), step=1)
        w.save(str(tmp_path), _state(2), step=2)  # fences+commits step 1
        with pytest.raises(TornWrite):
            w.wait()
    assert ckpt.latest_valid_step(str(tmp_path)) == 1
    assert os.path.isdir(tmp_path / "step_00000002.tmp")
    # the writer is not wedged: the next save (re)writes step 2 cleanly
    w.save(str(tmp_path), _state(2), step=2)
    w.close()
    assert ckpt.latest_valid_step(str(tmp_path)) == 2
    got = ckpt.restore_checkpoint(str(tmp_path), target=_state(0))
    assert float(np.asarray(got["w"])[0, 0]) == 2.0


def test_manager_gc_never_deletes_the_only_valid_checkpoint(tmp_path):
    # lay down 4 steps WITHOUT intermediate retention, then strip the
    # markers of the two newest (a pre-marker writer / lost-marker
    # scenario gc treats as legacy, not partial)
    for s in (1, 2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), _state(s), step=s)
    for s in (3, 4):
        os.remove(os.path.join(
            str(tmp_path), f"step_{s:08d}", ckpt.COMMIT_MARKER))
    m = ckpt.CheckpointManager(str(tmp_path), max_to_keep=2)
    # retention window is {3, 4} (both invalid); the newest VALID step
    # (2) must survive even though it aged out of the window
    m._gc()
    assert ckpt.latest_valid_step(str(tmp_path)) == 2
    assert not os.path.isdir(tmp_path / "step_00000001")
    assert os.path.isdir(tmp_path / "step_00000004")  # legacy: untouched
    got = m.restore(target=_state(0))
    # restore prefers the newest VALID step over the newer legacy dirs
    assert float(np.asarray(got["w"])[0, 0]) == 2.0


def test_manager_async_gc_spares_in_flight_write(tmp_path):
    m = ckpt.CheckpointManager(str(tmp_path), max_to_keep=1,
                               async_save=True)
    for s in (1, 2, 3):
        m.save(s, _state(s))
    m.wait_until_finished()
    assert ckpt.latest_valid_step(str(tmp_path)) == 3
    kept = [d for d in os.listdir(tmp_path) if d.startswith("step_")]
    assert kept == ["step_00000003"]


def test_markerless_legacy_dir_still_restores_and_survives_gc(tmp_path):
    p = ckpt.save_checkpoint(str(tmp_path), _state(7), step=7)
    os.remove(os.path.join(p, ckpt.COMMIT_MARKER))  # pre-marker writer
    assert ckpt.latest_valid_step(str(tmp_path)) is None
    assert ckpt.gc_partial_checkpoints(str(tmp_path)) == []
    got = ckpt.restore_checkpoint(str(tmp_path), target=_state(0))
    assert float(np.asarray(got["w"])[0, 0]) == 7.0


def test_overwrite_false_fails_fast_and_is_not_retryable(tmp_path):
    p = ckpt.save_checkpoint(str(tmp_path), _state(1), step=1)
    # ValueError (permanent condition), raised BEFORE any data lands:
    # no .tmp dir may be left behind and no retry policy should bite
    with pytest.raises(ValueError, match="overwrite=False"):
        ckpt.save_checkpoint(str(tmp_path), _state(2), step=1,
                             overwrite=False)
    assert not os.path.isdir(p + ckpt.TMP_SUFFIX)
    w = ckpt.AsyncCheckpointWriter()
    with pytest.raises(ValueError, match="overwrite=False"):
        w.save(str(tmp_path), _state(2), step=1, overwrite=False)
    w.close()
    got = ckpt.restore_checkpoint(str(tmp_path), target=_state(0))
    assert float(np.asarray(got["w"])[0, 0]) == 1.0


def test_max_to_keep_zero_keeps_everything(tmp_path):
    m = ckpt.CheckpointManager(str(tmp_path), max_to_keep=0)
    for s in (1, 2, 3, 4):
        m.save(s, _state(s))
    kept = sorted(d for d in os.listdir(tmp_path)
                  if d.startswith("step_"))
    assert len(kept) == 4
