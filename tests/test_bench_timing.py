"""Guardrails for bench.py's r5 timing methodology (host-fetch sync,
fetch-cost subtraction, on-device scan loops). These run on the CPU mesh;
the magnitudes they assert are loose — the point is that the machinery
returns sane, positive, finite numbers and the scan really iterates."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench  # repo root is on sys.path via tests/conftest.py


def test_sync_fetches_one_element():
    x = jnp.arange(12.0).reshape(3, 4)
    v = bench._sync(x)
    assert float(v) == 0.0  # element [0, 0]
    assert bench._sync(jnp.float32(7.0)) == 7.0
    assert bench._sync({"a": jnp.ones((2, 2))}) == 1.0


def test_sync_uses_last_leaf_and_tolerates_empty():
    """The LAST leaf is the sync anchor (a (*state, loss) step output
    enqueues it last), and an empty pytree is a no-op like
    block_until_ready, not an IndexError."""
    from apex_tpu.runtime import timing

    out = (jnp.zeros((2, 2)), jnp.full((3,), 5.0))
    assert float(timing.sync(out)) == 5.0
    assert timing.sync(()) is None
    assert timing.sync({}) is None


def test_fetch_cost_nonnegative_and_small_on_cpu():
    x = jnp.ones((4,))
    c = bench._fetch_cost(x)
    assert 0.0 <= c < 0.5  # ~zero locally; ~79ms through the tunnel


def test_cached_fetch_cost_measures_once():
    from apex_tpu.runtime import timing

    c1 = timing.cached_fetch_cost(jnp.ones((4,)))
    assert 0.0 <= c1 < 0.5
    # second call returns the cached constant without re-measuring
    assert timing.cached_fetch_cost(jnp.ones((8,))) == c1


def test_time_fn_measures_wall_and_subtracts_fetch():
    def slow():
        time.sleep(0.02)
        return jnp.zeros(())

    t = bench.time_fn(slow, iters=3, warmup=1)
    assert 0.015 < t < 0.2


def test_time_fn_max_time_caps_iters():
    calls = []

    def slow():
        calls.append(1)
        time.sleep(0.03)
        return jnp.zeros(())

    bench.time_fn(slow, iters=50, warmup=1, max_time_s=0.1)
    # warmup (1) + timed iters capped to ~0.1/0.03 = 3
    assert len(calls) <= 6


def test_time_scanned_per_iteration_magnitude():
    """time_scanned's per-iteration figure must match a directly-timed
    single iteration of the same op — a regression in the scan length or
    the (reps-1)*k divisor shifts the result by a factor of k and fails
    this band."""
    k = 8
    x = jnp.ones((768, 768), jnp.float32)

    def make_step():
        return lambda c: (c @ c) * 1e-6  # heavy enough to time on CPU

    # direct single-iteration time (compile + settle first)
    f = jax.jit(make_step())
    y = f(x)
    bench._sync(y)
    t0 = time.perf_counter()
    for _ in range(4):
        y = f(y)
    bench._sync(y)
    t_direct = (time.perf_counter() - t0) / 4

    t_scan = bench.time_scanned(make_step, x, lambda c, s: s(c), k=k,
                                reps=3)
    assert np.isfinite(t_scan) and t_scan > 0
    assert 0.25 * t_direct < t_scan < 4.0 * t_direct, (t_scan, t_direct)


def test_time_scanned_tuple_carry():
    def make_step():
        return lambda a, b: a + b

    def chain(c, step):
        return step(*c), c[1]

    t = bench.time_scanned(make_step,
                           (jnp.zeros((4,)), jnp.ones((4,))),
                           chain, k=4, reps=2)
    assert t >= 0.0 and np.isfinite(t)


def test_peak_flops_table():
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v4") == 275e12
    assert bench._peak_flops("unknown accelerator") is None
