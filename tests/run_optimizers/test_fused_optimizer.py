"""Optimizer parity tests (mirrors ref tests/L0/run_optimizers/test_fused_optimizer.py,
which checks the fused CUDA optimizers against torch.optim; here we check
against optax / hand-rolled numpy references)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FusedAdam, fused_adam,
    FusedSGD, fused_sgd,
    fused_lamb,
    fused_adagrad,
    fused_novograd,
    fused_mixed_precision_lamb,
)


def make_tree(seed=0, dtype=jnp.float32):
    k = jax.random.PRNGKey(seed)
    ks = jax.random.split(k, 4)
    return {
        "w1": jax.random.normal(ks[0], (17, 33), dtype),
        "b1": jax.random.normal(ks[1], (33,), dtype),
        "deep": {"w2": jax.random.normal(ks[2], (33, 5), dtype),
                 "b2": jax.random.normal(ks[3], (5,), dtype)},
    }


def run_steps(tx, params, n=5, seed=100):
    state = tx.init(params)
    for i in range(n):
        grads = jax.tree_util.tree_map(
            lambda p, i=i: jax.random.normal(jax.random.PRNGKey(seed + i), p.shape, p.dtype),
            params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


def assert_trees_close(a, b, rtol=2e-5, atol=2e-6):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                                rtol=rtol, atol=atol), a, b)


class TestFusedAdam:
    def test_matches_optax_adamw(self):
        params = make_tree()
        ours = run_steps(fused_adam(lr=1e-2, weight_decay=0.1, adam_w_mode=True), params)
        ref = run_steps(optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.1), params)
        assert_trees_close(ours, ref)

    def test_matches_optax_adam_l2_off(self):
        params = make_tree(1)
        ours = run_steps(fused_adam(lr=3e-3, weight_decay=0.0, adam_w_mode=False), params)
        ref = run_steps(optax.adam(3e-3), params)
        assert_trees_close(ours, ref)

    def test_flat_matches_tree(self):
        params = make_tree(2)
        ours = run_steps(fused_adam(lr=1e-2, weight_decay=0.05, flat=True), params)
        ref = run_steps(fused_adam(lr=1e-2, weight_decay=0.05, flat=False), params)
        assert_trees_close(ours, ref, rtol=1e-6, atol=1e-7)

    def test_flat_mixed_dtypes(self):
        params = {"a": jnp.ones((8, 8), jnp.bfloat16), "b": jnp.ones((4,), jnp.float32)}
        tx = fused_adam(lr=1e-2, flat=True)
        state = tx.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        updates, state = tx.update(grads, state, params)
        assert updates["a"].dtype == jnp.bfloat16
        assert updates["b"].dtype == jnp.float32

    def test_schedule_parity_with_optax(self):
        # lr schedules must see the same step index optax feeds them
        sched = optax.linear_schedule(0.0, 1e-2, transition_steps=5)
        params = make_tree(20)
        ours = run_steps(fused_adam(lr=sched, weight_decay=0.0, adam_w_mode=False), params)
        ref = run_steps(optax.adam(learning_rate=sched), params)
        assert_trees_close(ours, ref)

    def test_stateful_class(self):
        params = make_tree(3)
        opt = FusedAdam(params, lr=1e-2)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        new_params = opt.step(grads)
        assert not np.allclose(np.asarray(new_params["b1"]), np.asarray(params["b1"]))

    def test_amsgrad_raises(self):
        with pytest.raises(RuntimeError):
            FusedAdam(make_tree(), amsgrad=True)

    def test_state_dict_roundtrip(self):
        params = make_tree(4)
        opt = FusedAdam(params, lr=1e-2)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        opt.step(grads)
        sd = opt.state_dict()
        opt2 = FusedAdam(opt.params, lr=1e-2)
        opt2.load_state_dict(sd)
        a = opt.step(grads)
        b = opt2.step(grads)
        assert_trees_close(a, b, rtol=0, atol=0)


class TestTreeStructures:
    def test_tuple_valued_pytree(self):
        # params trees containing tuples are legal pytrees; the optimizers
        # must not confuse them with internal result packing
        params = {"layer": (jnp.ones((4, 4)), jnp.zeros((4,)))}
        grads = {"layer": (jnp.full((4, 4), 0.1), jnp.full((4,), 0.1))}
        for factory in (lambda: fused_adam(1e-2), lambda: fused_sgd(0.1, momentum=0.9),
                        lambda: fused_lamb(1e-2), lambda: fused_adagrad(1e-2),
                        lambda: fused_novograd(1e-2)):
            tx = factory()
            state = tx.init(params)
            updates, _ = tx.update(grads, state, params)
            assert isinstance(updates["layer"], tuple)

    def test_flat_fp32_grads_over_bf16_params(self):
        # standard mixed precision: bf16 params, fp32 grads
        params = {"w": jnp.ones((8, 8), jnp.bfloat16)}
        grads = {"w": jnp.full((8, 8), 0.1, jnp.float32)}
        tx = fused_adam(1e-2, flat=True)
        updates, _ = tx.update(grads, tx.init(params), params)
        assert updates["w"].dtype == jnp.bfloat16


class TestFusedSGD:
    def test_matches_optax_sgd_momentum(self):
        params = make_tree(5)
        ours = run_steps(fused_sgd(lr=0.1, momentum=0.9), params)
        ref = run_steps(optax.sgd(0.1, momentum=0.9), params)
        assert_trees_close(ours, ref)

    def test_nesterov(self):
        params = make_tree(6)
        ours = run_steps(fused_sgd(lr=0.1, momentum=0.9, nesterov=True), params)
        ref = run_steps(optax.sgd(0.1, momentum=0.9, nesterov=True), params)
        assert_trees_close(ours, ref)

    def test_plain(self):
        params = make_tree(7)
        ours = run_steps(fused_sgd(lr=0.05), params)
        ref = run_steps(optax.sgd(0.05), params)
        assert_trees_close(ours, ref)

    def test_nesterov_requires_momentum(self):
        with pytest.raises(ValueError):
            fused_sgd(lr=0.1, nesterov=True)

    def test_weight_decay_order_differs(self):
        params = make_tree(8)
        a = run_steps(fused_sgd(lr=0.1, momentum=0.9, weight_decay=0.1), params)
        b = run_steps(fused_sgd(lr=0.1, momentum=0.9, weight_decay=0.1,
                                wd_after_momentum=True), params)
        with pytest.raises(AssertionError):
            assert_trees_close(a, b)


class TestFusedAdagrad:
    def test_matches_numpy_reference(self):
        p0 = np.random.RandomState(0).randn(13, 7).astype(np.float32)
        g = np.random.RandomState(1).randn(13, 7).astype(np.float32)
        lr, eps, wd = 0.05, 1e-10, 0.02
        # numpy L2-mode adagrad
        p_ref, h = p0.copy(), np.zeros_like(p0)
        for _ in range(4):
            geff = g + wd * p_ref
            h += geff ** 2
            p_ref -= lr * geff / (np.sqrt(h) + eps)
        tx = fused_adagrad(lr=lr, eps=eps, weight_decay=wd)
        params = {"p": jnp.asarray(p0)}
        state = tx.init(params)
        for _ in range(4):
            updates, state = tx.update({"p": jnp.asarray(g)}, state, params)
            params = optax.apply_updates(params, updates)
        np.testing.assert_allclose(np.asarray(params["p"]), p_ref, rtol=1e-5, atol=1e-6)


class TestFusedLAMB:
    def test_decreases_loss(self):
        params = make_tree(9)
        tx = fused_lamb(lr=1e-2, weight_decay=0.01)
        state = tx.init(params)

        def loss_fn(p):
            return sum(jnp.sum(jnp.square(l)) for l in jax.tree_util.tree_leaves(p))

        loss0 = loss_fn(params)
        for _ in range(10):
            grads = jax.grad(loss_fn)(params)
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        assert loss_fn(params) < loss0

    def test_trust_ratio_gating(self):
        # with use_nvlamb=False and wd=0, update reduces to plain clipped adam
        params = {"p": jnp.ones((4, 4))}
        grads = {"p": jnp.full((4, 4), 0.1)}
        tx = fused_lamb(lr=1e-2, weight_decay=0.0, use_nvlamb=False, max_grad_norm=1e9)
        adam = fused_adam(lr=1e-2, weight_decay=0.0, eps=1e-6)
        s1, s2 = tx.init(params), adam.init(params)
        u1, _ = tx.update(grads, s1, params)
        u2, _ = adam.update(grads, s2, params)
        assert_trees_close(u1, u2)

    def test_l2_mode_differs_from_adamw(self):
        # L2 mode folds decay into the moments (MOMENT_MODE_0); AdamW adds it
        # post-hoc — trajectories must diverge over steps
        params = make_tree(11)
        a = run_steps(fused_lamb(lr=1e-2, weight_decay=0.1, adam_w_mode=False), params)
        b = run_steps(fused_lamb(lr=1e-2, weight_decay=0.1, adam_w_mode=True), params)
        assert not np.allclose(np.asarray(a["b1"]), np.asarray(b["b1"]))

    def test_clipping_scales_moments(self):
        # A single LAMB step is scale-invariant (adam direction), so verify
        # clipping through the moments: grads of norm 40 clipped to norm 1
        # must produce moments 40x smaller.
        params = {"p": jnp.ones((4, 4))}
        grads = {"p": jnp.full((4, 4), 10.0)}  # global norm 40 >> 1
        tx_clip = fused_lamb(lr=1e-2, max_grad_norm=1.0)
        tx_noclip = fused_lamb(lr=1e-2, max_grad_norm=1e9)
        _, s1 = tx_clip.update(grads, tx_clip.init(params), params)
        _, s2 = tx_noclip.update(grads, tx_noclip.init(params), params)
        np.testing.assert_allclose(
            np.asarray(s1.mu["p"]) * 40.0, np.asarray(s2.mu["p"]), rtol=1e-5)


class TestFusedNovoGrad:
    def test_first_step_norm_seed(self):
        # init_zero=False: first step behaves like SGD step of size lr*(1-b1)
        params = {"p": jnp.ones((3, 3))}
        g = jnp.full((3, 3), 2.0)
        tx = fused_novograd(lr=0.1, betas=(0.9, 0.99), eps=0.0,
                            bias_correction=False, init_zero=False)
        updates, _ = tx.update({"p": g}, tx.init(params), params)
        gnorm = float(jnp.sqrt(jnp.sum(g ** 2)))
        expected = -0.1 * (1 - 0.9) * (2.0 / gnorm)
        np.testing.assert_allclose(np.asarray(updates["p"]),
                                   np.full((3, 3), expected), rtol=1e-5)

    def test_l2_blend_root_of_squares(self):
        # norm_type=2 blends sqrt(b2*v^2 + (1-b2)*n^2), not linearly
        params = {"p": jnp.ones((2, 2))}
        tx = fused_novograd(lr=0.1, betas=(0.9, 0.5), eps=0.0,
                            bias_correction=False, init_zero=False)
        state = tx.init(params)
        g1 = jnp.full((2, 2), 1.0)   # norm 2
        g2 = jnp.full((2, 2), 2.0)   # norm 4
        _, state = tx.update({"p": g1}, state, params)
        _, state = tx.update({"p": g2}, state, params)
        expected = np.sqrt(0.5 * 2.0 ** 2 + 0.5 * 4.0 ** 2)
        np.testing.assert_allclose(float(state.v_norm["p"]), expected, rtol=1e-6)

    def test_bias_correction_scales_first_update(self):
        # with bias correction, step-1 denominator shrinks by sqrt(1-b2)
        # and the numerator grows by 1/(1-b1): update = -lr * g/gnorm * sqrt(1-b2)... inverted
        params = {"p": jnp.ones((3, 3))}
        g = jnp.full((3, 3), 2.0)
        gnorm = float(jnp.sqrt(jnp.sum(g ** 2)))
        tx = fused_novograd(lr=0.1, betas=(0.9, 0.99), eps=0.0,
                            bias_correction=True, init_zero=False)
        updates, _ = tx.update({"p": g}, tx.init(params), params)
        # m_hat = m/(1-b1) = g/v_hat ; v_hat = gnorm/sqrt(1-b2^1)
        v_hat = gnorm / np.sqrt(1 - 0.99)
        expected = -0.1 * (2.0 / v_hat)
        np.testing.assert_allclose(np.asarray(updates["p"]),
                                   np.full((3, 3), expected), rtol=1e-5)

    def test_decreases_loss(self):
        params = make_tree(10)
        tx = fused_novograd(lr=1e-2)
        state = tx.init(params)

        def loss_fn(p):
            return sum(jnp.sum(jnp.square(l)) for l in jax.tree_util.tree_leaves(p))

        loss0 = loss_fn(params)
        for _ in range(10):
            grads = jax.grad(loss_fn)(params)
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        assert loss_fn(params) < loss0


class TestFusedMixedPrecisionLamb:
    def test_bf16_params_fp32_master(self):
        params = {"p": jnp.ones((16, 16), jnp.bfloat16)}
        tx = fused_mixed_precision_lamb(lr=1e-3)
        state = tx.init(params)
        assert state.master["p"].dtype == jnp.float32
        grads = {"p": jnp.full((16, 16), 0.01, jnp.bfloat16)}
        for _ in range(3):
            updates, state = tx.update(grads, state, params)
            assert updates["p"].dtype == jnp.bfloat16
            params = optax.apply_updates(params, updates)
        # master tracks finer resolution than bf16 params
        assert state.master["p"].dtype == jnp.float32
