"""DistributedFusedLAMB (ZeRO LAMB) parity vs unsharded FusedLAMB on the
dp mesh (VERDICT next-round #7; ref apex/contrib/optimizers/
distributed_fused_lamb.py)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from apex_tpu.contrib.optimizers import distributed_fused_lamb
from apex_tpu.optimizers import fused_lamb


def mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("dp",))


def _params():
    # deliberately awkward sizes: padding + tensors straddling shard
    # boundaries exercise the segment-sum norm path
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 3)
    return {
        "w": jax.random.normal(ks[0], (37, 5)),
        "b": jax.random.normal(ks[1], (11,)) * 0.1,
        "v": jax.random.normal(ks[2], (3,)),
    }


def _grads():
    k = jax.random.PRNGKey(1)
    ks = jax.random.split(k, 3)
    return {
        "w": jax.random.normal(ks[0], (37, 5)) * 0.3,
        "b": jax.random.normal(ks[1], (11,)),
        "v": jax.random.normal(ks[2], (3,)) * 2.0,
    }


def test_matches_unsharded_lamb_one_step():
    mesh = mesh8()
    params, grads = _params(), _grads()
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    tx = distributed_fused_lamb(axis_name="dp", **kw)

    def run(params, grads):
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        return updates

    got = shard_map(run, mesh=mesh, in_specs=(P(), P()),
                    out_specs=P())(params, grads)

    ref_tx = fused_lamb(**kw)
    st = ref_tx.init(params)
    want, _ = ref_tx.update(grads, st, params)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_matches_unsharded_lamb_trajectory():
    """Three steps with different grads: moments and bias correction stay
    in sync with the unsharded optimizer."""
    mesh = mesh8()
    params = _params()
    kw = dict(lr=5e-3, weight_decay=0.1, max_grad_norm=0.5,
              use_nvlamb=True)
    tx = distributed_fused_lamb(axis_name="dp", **kw)
    ref_tx = fused_lamb(**kw)

    def run(params, g1, g2, g3):
        state = tx.init(params)
        p = params
        for g in (g1, g2, g3):
            updates, state = tx.update(g, state, p)
            p = jax.tree_util.tree_map(jnp.add, p, updates)
        return p

    gs = [jax.tree_util.tree_map(
        lambda a, i=i: a * (0.5 + i), _grads()) for i in range(3)]
    got = jax.jit(shard_map(run, mesh=mesh, in_specs=(P(),) * 4,
                            out_specs=P()))(params, *gs)

    st = ref_tx.init(params)
    p = params
    for g in gs:
        updates, st = ref_tx.update(g, st, p)
        p = jax.tree_util.tree_map(jnp.add, p, updates)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(p[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_state_is_sharded():
    """ZeRO point: each rank's master/m/v shard is 1/8 of the padded flat
    size."""
    mesh = mesh8()
    params = _params()
    total = sum(p.size for p in jax.tree_util.tree_leaves(params))
    tx = distributed_fused_lamb(axis_name="dp")

    def run(params):
        state = tx.init(params)
        return state.master_shard["float32"].size

    out = shard_map(
        lambda p: jnp.asarray(run(p)), mesh=mesh, in_specs=(P(),),
        out_specs=P())(params)
    padded = total + (-total) % 8
    assert int(out) == padded // 8


def test_contrib_optimizer_imports():
    """Import-surface parity (ref apex/contrib/optimizers/*)."""
    from apex_tpu.contrib.optimizers import (  # noqa: F401
        FP16_Optimizer,
        DistributedFusedAdam,
        DistributedFusedLAMB,
    )
    from apex_tpu.contrib.optimizers.distributed_fused_adam_v2 import (  # noqa: F401
        DistributedFusedAdamV2,
    )
    from apex_tpu.contrib.optimizers.distributed_fused_adam_v3 import (  # noqa: F401
        DistributedFusedAdamV3,
    )
    from apex_tpu.contrib.optimizers.fused_adam import FusedAdam  # noqa: F401
    from apex_tpu.contrib.optimizers.fused_lamb import FusedLAMB  # noqa: F401
    from apex_tpu.contrib.optimizers.fused_sgd import FusedSGD  # noqa: F401


def test_dp4_parity_and_rank_consistency():
    """VERDICT r4 #8: dp=4 parity vs the unsharded optimizer, plus the
    all-gather invariant — every rank must hold BITWISE-identical updated
    params (the psum-placement gather makes them invariant by
    construction; this asserts it survives refactors)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    params, grads = _params(), _grads()
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    tx = distributed_fused_lamb(axis_name="dp", **kw)

    def run(params, grads):
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        return updates

    # stack each rank's copy (mark varying + leading rank dim) so the
    # cross-rank comparison is a real bitwise check, not a vma property
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    per_rank = jax.jit(shard_map(
        lambda p, g: jax.tree_util.tree_map(
            lambda u: _to_varying(u, "dp")[None], run(p, g)),
        mesh=mesh, in_specs=(P(), P()), out_specs=P("dp")))(params, grads)

    ref_tx = fused_lamb(**kw)
    st = ref_tx.init(params)
    want, _ = ref_tx.update(grads, st, params)
    for k in params:
        ranks = np.asarray(per_rank[k])
        for r in range(1, 4):
            np.testing.assert_array_equal(
                ranks[0], ranks[r],
                err_msg=f"{k}: rank {r} diverged bitwise from rank 0")
        np.testing.assert_allclose(ranks[0], np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_master_dtype_bf16_halves_state_and_stays_close():
    """master_dtype=bf16: ZeRO state stored in bf16 (memory knob), step
    math still fp32 — one step lands within bf16 rounding of the fp32-
    master run."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    params, grads = _params(), _grads()
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)

    def run_with(master_dtype):
        tx = distributed_fused_lamb(axis_name="dp",
                                    master_dtype=master_dtype, **kw)

        def run(params, grads):
            state = tx.init(params)
            assert state.master_shard["float32"].dtype == master_dtype
            assert state.mu_shard["float32"].dtype == master_dtype
            updates, _ = tx.update(grads, state, params)
            return updates

        return jax.jit(shard_map(run, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=P()))(params, grads)

    full = run_with(jnp.float32)
    half = run_with(jnp.bfloat16)
    for k in params:
        # the dominant term is the one-time bf16 rounding of the master
        # COPY of the params (~eps_bf16 * |p|), which lands in the first
        # update verbatim; subsequent drift is much smaller
        np.testing.assert_allclose(
            np.asarray(half[k]), np.asarray(full[k]), rtol=2e-2,
            atol=1e-2, err_msg=k)


def test_bf16_reduce_scatter_close_to_fp32():
    """fp32_reduce_scatter=False reduces grads on the wire in their own
    dtype; with bf16 grads the update stays within bf16 tolerance."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    params = _params()
    grads16 = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.bfloat16), _grads())
    kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)

    def run_with(fp32_rs):
        tx = distributed_fused_lamb(axis_name="dp",
                                    fp32_reduce_scatter=fp32_rs, **kw)

        def run(params, grads):
            state = tx.init(params)
            updates, _ = tx.update(grads, state, params)
            return updates

        return jax.jit(shard_map(run, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=P()))(params, grads16)

    a = run_with(True)
    b = run_with(False)
    for k in params:
        np.testing.assert_allclose(np.asarray(b[k]), np.asarray(a[k]),
                                   rtol=2e-2, atol=2e-3, err_msg=k)
