"""Param groups (ref tests/L0/run_amp/test_add_param_group.py): a second
group with its own lr/weight_decay must update with those hyperparameters
while the first group is unaffected."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam, FusedSGD


def _params(seed, n=3):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (n, n)), "b": jnp.zeros((n,))}


def test_add_param_group_separate_hyperparams():
    p0, p1 = _params(0), _params(1)
    opt = FusedAdam(p0, lr=1e-3, weight_decay=0.0)
    opt.add_param_group({"params": p1, "lr": 1e-1})
    assert len(opt.param_groups) == 2
    assert opt.param_groups[1]["lr"] == 1e-1

    g0 = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.1), p0)
    g1 = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.1), p1)
    new0, new1 = opt.step([g0, g1])

    # group 1 (lr 100x) must move ~100x further on the first Adam step?
    # Adam normalizes by sqrt(v), so the first-step move is ~lr exactly.
    d0 = float(jnp.max(jnp.abs(new0["w"] - p0["w"])))
    d1 = float(jnp.max(jnp.abs(new1["w"] - p1["w"])))
    np.testing.assert_allclose(d0, 1e-3, rtol=1e-3)
    np.testing.assert_allclose(d1, 1e-1, rtol=1e-3)


def test_add_param_group_matches_separate_optimizers():
    """Two groups must evolve exactly as two independent optimizers."""
    p0, p1 = _params(0), _params(1)
    opt = FusedAdam(p0, lr=1e-3)
    opt.add_param_group({"params": p1, "lr": 3e-3, "weight_decay": 0.1})
    ref0 = FusedAdam(_params(0), lr=1e-3)
    ref1 = FusedAdam(_params(1), lr=3e-3, weight_decay=0.1)

    for i in range(3):
        g0 = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, 0.01 * (i + 1)), p0)
        g1 = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, 0.02 * (i + 1)), p1)
        new0, new1 = opt.step([g0, g1])
        r0 = ref0.step(g0)
        r1 = ref1.step(g1)
    for a, b in ((new0, r0), (new1, r1)):
        for ka in a:
            np.testing.assert_allclose(np.asarray(a[ka]), np.asarray(b[ka]),
                                       rtol=1e-6)


def test_add_param_group_sgd():
    p0, p1 = _params(0), _params(1)
    opt = FusedSGD(p0, lr=0.1, momentum=0.9)
    opt.add_param_group({"params": p1, "lr": 0.01})
    g = jax.tree_util.tree_map(jnp.ones_like, p0)
    new0, new1 = opt.step([g, jax.tree_util.tree_map(jnp.ones_like, p1)])
    np.testing.assert_allclose(np.asarray(p0["w"] - new0["w"]), 0.1,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p1["w"] - new1["w"]), 0.01,
                               rtol=1e-6)


def test_add_param_group_validation():
    opt = FusedAdam(_params(0), lr=1e-3)
    with pytest.raises(ValueError):
        opt.add_param_group({"lr": 1e-2})                    # no params
    with pytest.raises(ValueError):
        opt.add_param_group({"params": _params(1), "momentum": 0.9})  # unknown
    opt.add_param_group({"params": _params(1)})
    with pytest.raises(ValueError):  # single tree once a 2nd group exists
        opt.step(jax.tree_util.tree_map(jnp.ones_like, _params(0)))
    with pytest.raises(ValueError):  # wrong number of grad trees
        opt.step([jax.tree_util.tree_map(jnp.ones_like, _params(0))])


def test_param_groups_view_stays_fresh():
    """param_groups[i]['params'] must track the live params after step()
    in both the single-group and multi-group paths (torch idiom)."""
    p0 = _params(0)
    opt = FusedAdam(p0, lr=1e-3)
    g0 = jax.tree_util.tree_map(jnp.ones_like, p0)
    new0 = opt.step(g0)
    assert opt.param_groups[0]["params"] is new0
    opt.add_param_group({"params": _params(1)})
    out = opt.step([g0, jax.tree_util.tree_map(jnp.ones_like, _params(1))])
    assert opt.param_groups[0]["params"] is out[0]
    assert opt.param_groups[1]["params"] is out[1]


def test_state_dict_roundtrip_with_groups():
    p0, p1 = _params(0), _params(1)
    opt = FusedAdam(p0, lr=1e-3)
    opt.add_param_group({"params": p1, "lr": 1e-2})
    g = [jax.tree_util.tree_map(jnp.ones_like, p0),
         jax.tree_util.tree_map(jnp.ones_like, p1)]
    opt.step(g)
    sd = opt.state_dict()

    opt2 = FusedAdam(p0, lr=1e-3)
    opt2.add_param_group({"params": p1, "lr": 1e-2})
    opt2.load_state_dict(sd)
    # params live outside state_dict (torch parity); resume from the same
    # params so identical state must give identical updates
    opt2.params = opt.params
    opt2._extra_groups[0]["params"] = opt._extra_groups[0]["params"]
    a = opt.step(g)
    b = opt2.step(g)
    for ta, tb in zip(jax.tree_util.tree_leaves(a[1]),
                      jax.tree_util.tree_leaves(b[1])):
        np.testing.assert_allclose(np.asarray(ta), np.asarray(tb), rtol=1e-6)


def test_scheduler_idiom_lr_mutation_takes_effect():
    """The torch LR-scheduler idiom — writing param_groups[i]['lr'] —
    must change the next step's update magnitude."""
    p0 = _params(0)
    opt = FusedAdam(p0, lr=1e-3)
    g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.1), p0)
    new1 = opt.step(g)
    d1 = float(jnp.max(jnp.abs(new1["w"] - p0["w"])))
    np.testing.assert_allclose(d1, 1e-3, rtol=1e-3)  # first Adam step ~ lr

    for group in opt.param_groups:
        group["lr"] = 1e-1  # scheduler writes the group dict in place
    new2 = opt.step(g)
    d2 = float(jnp.max(jnp.abs(new2["w"] - new1["w"])))
    np.testing.assert_allclose(d2, 1e-1, rtol=2e-2)

    # extra groups honor it too
    p1 = _params(1)
    opt.add_param_group({"params": p1, "lr": 1e-3})
    opt.param_groups[1]["lr"] = 5e-2
    outs = opt.step([g, jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, 0.1), p1)])
    d3 = float(jnp.max(jnp.abs(outs[1]["w"] - p1["w"])))
    np.testing.assert_allclose(d3, 5e-2, rtol=2e-2)
