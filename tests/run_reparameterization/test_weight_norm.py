"""Weight norm tests vs torch.nn.utils.weight_norm semantics."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.reparameterization import (
    WeightNorm,
    apply_weight_norm,
    compute_weights,
    remove_weight_norm,
)


def test_reparameterize_roundtrip():
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    g, v = WeightNorm.reparameterize(w, dim=0)
    w2 = WeightNorm.compute_weight(g, v, dim=0)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w), rtol=1e-5,
                               atol=1e-6)


def test_norm_dim_semantics():
    """dim=0: per-output-row norms (torch weight_norm default)."""
    w = jax.random.normal(jax.random.PRNGKey(1), (8, 4))
    g, _ = WeightNorm.reparameterize(w, dim=0)
    want = np.linalg.norm(np.asarray(w), axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5)
    g_all, _ = WeightNorm.reparameterize(w, dim=None)
    np.testing.assert_allclose(float(g_all), np.linalg.norm(np.asarray(w)),
                               rtol=1e-5)


def test_tree_apply_and_remove():
    params = {"layer": {"w": jnp.ones((4, 4)), "b": jnp.zeros(4)}}
    rp = apply_weight_norm(params)
    assert set(rp["layer"]) == {"w_g", "w_v", "b"}  # 1-d b untouched
    back = remove_weight_norm(rp)
    np.testing.assert_allclose(np.asarray(back["layer"]["w"]),
                               np.ones((4, 4)), rtol=1e-5)


def test_gradient_decoupling():
    """Grad wrt g scales magnitude only — the weight-norm property."""
    w = jax.random.normal(jax.random.PRNGKey(2), (4, 3))
    g, v = WeightNorm.reparameterize(w, dim=0)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3))

    def loss(g):
        return jnp.sum((x @ WeightNorm.compute_weight(g, v, 0).T) ** 2)

    dg = jax.grad(loss)(g)
    assert dg.shape == g.shape
    assert np.isfinite(np.asarray(dg)).all()


def test_inside_forward_trains():
    params = apply_weight_norm({"w": jnp.ones((4, 4)) * 0.3})
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4))

    def loss(p):
        w = compute_weights(p)["w"]
        return jnp.mean((x @ w) ** 2)

    grads = jax.grad(loss)(params)
    assert set(grads) == {"w_g", "w_v"}
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(grads))
