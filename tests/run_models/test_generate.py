"""KV-cache decoding (models/generate.py): internal teacher-forcing
consistency plus token-level parity with HF generate on imported
weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import generate as gen
from apex_tpu.models import llama


def test_greedy_matches_teacher_forcing():
    """Every generated token must equal the argmax of the full
    (non-cached) forward at its position — the cache path and the
    training path are the same function."""
    cfg = llama.tiny(num_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)

    out = jax.jit(lambda p, t: gen.greedy_generate(p, t, cfg, 6))(
        params, prompt)
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(np.asarray(out[:, :8]),
                                  np.asarray(prompt))

    logits = llama.forward(params, out, cfg, tp_axis=None, cp_axis=None,
                           remat=False)
    preds = np.asarray(jnp.argmax(logits, axis=-1))
    got = np.asarray(out)
    for t in range(8 - 1, 14 - 1):
        np.testing.assert_array_equal(
            got[:, t + 1], preds[:, t],
            err_msg=f"cached decode diverged at position {t + 1}")


def test_moe_greedy_matches_teacher_forcing():
    """MoE decode (VERDICT r4 missing #3): the no-drop inference router
    must reproduce the training forward exactly when the training path's
    capacity is large enough that it drops nothing either."""
    cfg = llama.tiny(num_layers=2, num_experts=4, moe_capacity_factor=8.0)
    assert cfg.moe
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)

    out = jax.jit(lambda p, t: gen.greedy_generate(p, t, cfg, 6))(
        params, prompt)
    assert out.shape == (2, 14)

    logits = llama.forward(params, out, cfg, tp_axis=None, cp_axis=None,
                           remat=False)
    preds = np.asarray(jnp.argmax(logits, axis=-1))
    got = np.asarray(out)
    for t in range(8 - 1, 14 - 1):
        np.testing.assert_array_equal(
            got[:, t + 1], preds[:, t],
            err_msg=f"moe cached decode diverged at position {t + 1}")


def test_moe_top1_switch_decode_runs():
    """Switch routing (top-1) keeps the RAW router prob as the gate —
    the decode router must preserve that (no renorm to 1.0)."""
    cfg = llama.tiny(num_layers=1, num_experts=4, moe_top_k=1,
                     moe_capacity_factor=8.0)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0,
                                cfg.vocab_size)
    out = gen.greedy_generate(params, prompt, cfg, 4)
    assert out.shape == (2, 10)
    logits = llama.forward(params, out, cfg, tp_axis=None, cp_axis=None,
                           remat=False)
    preds = np.asarray(jnp.argmax(logits, axis=-1))
    np.testing.assert_array_equal(np.asarray(out)[:, 6:],
                                  preds[:, 5:-1])


def test_decode_attention_gqa_matches_repeat_reference():
    """The grouped-einsum GQA decode attention (ISSUE 20 satellite)
    must be BIT-identical to the materialized jnp.repeat reference it
    replaced — same fp32 contractions over d and T, only the rep×
    cache copy removed — for scalar pos and for the serving
    scheduler's per-row [b, 1, 1] pos."""
    key = jax.random.PRNGKey(0)
    b, T, nkv, rep, d = 3, 16, 2, 3, 8
    nq = nkv * rep
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, 1, nq, d), jnp.float32)
    k_cache = jax.random.normal(kk, (b, T, nkv, d), jnp.float32)
    v_cache = jax.random.normal(kv, (b, T, nkv, d), jnp.float32)

    def reference(q, k_cache, v_cache, pos):
        k = jnp.repeat(k_cache, rep, axis=2)      # [b, T, nq, d]
        v = jnp.repeat(v_cache, rep, axis=2)
        scores = jnp.einsum("bqnd,btnd->bnt", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * (d ** -0.5)
        idx = jnp.arange(T)
        scores = jnp.where(idx[None, None, :] <= pos, scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bnt,btnd->bnd", probs, v.astype(jnp.float32))
        return o.reshape(b, 1, nq * d)

    for pos in (0, 9, T - 1):
        want = np.asarray(reference(q, k_cache, v_cache, pos))
        got = np.asarray(gen._decode_attention(q, k_cache, v_cache, pos))
        np.testing.assert_array_equal(
            got, want, err_msg=f"grouped GQA attention diverged from "
                               f"the repeat reference at pos={pos}")

    # per-row positions (serving packed batch): each row must equal the
    # scalar-pos result for its own position
    rows = np.array([2, 9, 15])
    got = np.asarray(gen._decode_attention(
        q, k_cache, v_cache, jnp.asarray(rows)[:, None, None]))
    for i, p in enumerate(rows):
        want_i = np.asarray(reference(q, k_cache, v_cache, int(p)))[i]
        np.testing.assert_array_equal(
            got[i], want_i,
            err_msg=f"per-row pos diverged for row {i} (pos {p})")

    # bf16 caches exercise the astype path generate() actually runs
    got16 = np.asarray(gen._decode_attention(
        q.astype(jnp.bfloat16), k_cache.astype(jnp.bfloat16),
        v_cache.astype(jnp.bfloat16), 9))
    want16 = np.asarray(reference(
        q.astype(jnp.bfloat16), k_cache.astype(jnp.bfloat16),
        v_cache.astype(jnp.bfloat16), 9))
    np.testing.assert_array_equal(got16, want16)


def test_temperature_sampling_runs():
    cfg = llama.tiny(num_layers=1)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0,
                                cfg.vocab_size)
    out = gen.generate(params, prompt, cfg, 5, temperature=1.0,
                       key=jax.random.PRNGKey(7))
    assert out.shape == (1, 9)
    with pytest.raises(ValueError, match="PRNG key"):
        gen.generate(params, prompt, cfg, 2, temperature=0.5)


@pytest.mark.slow
def test_matches_hf_generate():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from apex_tpu.models import convert

    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    params, cfg = convert.llama_from_hf(hf, dtype=jnp.float32)

    prompt = np.random.default_rng(3).integers(0, 256, (2, 8))
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0).numpy()
    got = np.asarray(gen.greedy_generate(params, jnp.asarray(prompt),
                                         cfg, 8))
    np.testing.assert_array_equal(got, want)


def test_gpt2_greedy_matches_teacher_forcing():
    from apex_tpu.models import gpt2

    cfg = gpt2.tiny()
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    out = jax.jit(lambda p, t: gen.gpt2_generate(p, t, cfg, 6))(
        params, prompt)
    assert out.shape == (2, 14)

    logits = gpt2.forward(params, out, cfg, tp_axis=None, remat=False)
    preds = np.asarray(jnp.argmax(logits, axis=-1))
    got = np.asarray(out)
    for t in range(7, 13):
        np.testing.assert_array_equal(
            got[:, t + 1], preds[:, t],
            err_msg=f"gpt2 cached decode diverged at position {t + 1}")


@pytest.mark.slow
def test_gpt2_matches_hf_generate():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from apex_tpu.models import convert

    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    params, cfg = convert.gpt2_from_hf(hf, dtype=jnp.float32)

    prompt = np.random.default_rng(4).integers(0, 256, (2, 8))
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0).numpy()
    got = np.asarray(gen.gpt2_generate(params, jnp.asarray(prompt),
                                       cfg, 8))
    np.testing.assert_array_equal(got, want)
