"""HF checkpoint import parity: converted weights must reproduce the
torch reference implementation's logits (models/convert.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from apex_tpu.models import convert, gpt2, llama  # noqa: E402


@pytest.mark.slow
def test_llama_logit_parity():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    params, cfg = convert.llama_from_hf(hf, dtype=jnp.float32)
    assert cfg.num_kv_heads == 2 and cfg.num_layers == 2

    tokens = np.random.default_rng(0).integers(0, 256, (2, 16))
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens)).logits.numpy()
    got = np.asarray(jax.jit(
        lambda p, t: llama.forward(p, t, cfg, tp_axis=None, cp_axis=None,
                                   remat=False))(params,
                                                 jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt2_logit_parity():
    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    params, cfg = convert.gpt2_from_hf(hf, dtype=jnp.float32)

    tokens = np.random.default_rng(1).integers(0, 256, (2, 16))
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens)).logits.numpy()
    got = np.asarray(jax.jit(
        lambda p, t: gpt2.forward(p, t, cfg, tp_axis=None,
                                  remat=False))(params,
                                                jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_bert_logit_parity():
    hf_cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    hf = transformers.BertForMaskedLM(hf_cfg).eval()
    # a real checkpoint carries a nonzero decoder bias — force one so the
    # parity actually exercises mlm_decoder_bias
    with torch.no_grad():
        hf.cls.predictions.bias.uniform_(-0.1, 0.1)

    from apex_tpu.models import bert

    params, cfg = convert.bert_from_hf(hf, dtype=jnp.float32)

    tokens = np.random.default_rng(2).integers(0, 256, (2, 16))
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens)).logits.numpy()

    def fwd(p, t):
        hidden = bert.forward(p, t, cfg, tp_axis=None, remat=False)
        return bert.mlm_logits(p, hidden, cfg, tp_axis=None)

    got = np.asarray(jax.jit(fwd)(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
