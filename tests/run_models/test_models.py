"""Model-zoo tests: single-device forward/loss, tp-sharded parity vs
unsharded, cp ring-attention parity, short training-loss decrease."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu.models import bert, dcgan, gpt2, llama, mlp, resnet
import optax

from apex_tpu.optimizers import fused_adam


def tp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


# ------------------------------------------------------------------- llama


class TestLlama:
    def test_forward_shape(self):
        cfg = llama.tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        logits = llama.forward(params, tokens, cfg, tp_axis=None, cp_axis=None)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_tp_parity(self):
        cfg = llama.tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref = llama.forward(params, tokens, cfg, tp_axis=None, cp_axis=None)

        mesh = tp_mesh(2)
        pspecs = llama.param_specs(cfg)
        fwd = shard_map(
            functools.partial(llama.forward, cfg=cfg, tp_axis="tp",
                              cp_axis=None),
            mesh=mesh, in_specs=(pspecs, P()), out_specs=P(None, None, "tp"),
        )
        out = fwd(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_tp_sp_parity(self):
        cfg = llama.tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref = llama.loss_fn(params, (tokens, tokens), cfg, tp_axis=None,
                            cp_axis=None)
        mesh = tp_mesh(2)
        loss = shard_map(
            functools.partial(llama.loss_fn, cfg=cfg, tp_axis="tp",
                              cp_axis=None, sequence_parallel=True),
            mesh=mesh, in_specs=(llama.param_specs(cfg), (P(), P())),
            out_specs=P(),
        )(params, (tokens, tokens))
        np.testing.assert_allclose(float(loss), float(ref), atol=2e-4,
                                   rtol=2e-4)

    def test_cp_parity(self):
        cfg = llama.tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                    cfg.vocab_size)
        ref = llama.forward(params, tokens, cfg, tp_axis=None, cp_axis=None)

        mesh = Mesh(np.array(jax.devices()[:4]), ("cp",))
        fwd = shard_map(
            functools.partial(llama.forward, cfg=cfg, tp_axis=None,
                              cp_axis="cp"),
            mesh=mesh, in_specs=(P(), P(None, "cp")),
            out_specs=P(None, "cp", None),
        )
        out = fwd(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_train_loss_decreases(self):
        cfg = llama.tiny(num_layers=1)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                    cfg.vocab_size)
        tx = fused_adam(lr=1e-2)
        state = tx.init(params)
        lfn = functools.partial(llama.loss_fn, cfg=cfg, tp_axis=None,
                                cp_axis=None)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(lfn)(params, (tokens, tokens))
            updates, state = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), state, loss

        first = None
        for _ in range(10):
            params, state, loss = step(params, state)
            first = loss if first is None else first
        assert float(loss) < float(first)

    def test_stage_split_roundtrip(self):
        cfg = llama.tiny(num_layers=4)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        staged = llama.split_stages(params, 2)
        assert staged["wq"].shape[0] == 2 and staged["wq"].shape[1] == 2


# -------------------------------------------------------------------- gpt2


class TestGPT2:
    def test_forward_and_loss(self):
        cfg = gpt2.tiny()
        params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        logits = gpt2.forward(params, tokens, cfg, tp_axis=None)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss = gpt2.loss_fn(params, (tokens, tokens), cfg, tp_axis=None)
        assert np.isfinite(float(loss))

    def test_tp_parity(self):
        cfg = gpt2.tiny()
        params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref = gpt2.loss_fn(params, (tokens, tokens), cfg, tp_axis=None)
        mesh = tp_mesh(2)
        loss = shard_map(
            functools.partial(gpt2.loss_fn, cfg=cfg, tp_axis="tp"),
            mesh=mesh, in_specs=(gpt2.param_specs(cfg), (P(), P())),
            out_specs=P(),
        )(params, (tokens, tokens))
        np.testing.assert_allclose(float(loss), float(ref), atol=2e-4,
                                   rtol=2e-4)

    def test_causality(self):
        cfg = gpt2.tiny()
        params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                cfg.vocab_size)
        t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab_size)
        l1 = gpt2.forward(params, t1, cfg, tp_axis=None)
        l2 = gpt2.forward(params, t2, cfg, tp_axis=None)
        np.testing.assert_allclose(np.asarray(l1[0, :10]),
                                   np.asarray(l2[0, :10]), atol=1e-5)
        assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


# -------------------------------------------------------------------- bert


class TestBert:
    def test_forward_and_loss(self):
        cfg = bert.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        mask = jnp.zeros((2, 16), bool).at[:, 12:].set(True)
        hidden = bert.forward(params, tokens, cfg, pad_mask=mask,
                              tp_axis=None)
        assert hidden.shape == (2, 16, cfg.hidden_size)
        loss_mask = jnp.zeros((2, 16)).at[:, 3:6].set(1.0)
        loss = bert.loss_fn(params, (tokens, tokens, loss_mask), cfg,
                            tp_axis=None)
        assert np.isfinite(float(loss))

    def test_tp_parity(self):
        cfg = bert.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        loss_mask = jnp.ones((2, 16))
        ref = bert.loss_fn(params, (tokens, tokens, loss_mask), cfg,
                           tp_axis=None)
        mesh = tp_mesh(2)
        loss = shard_map(
            functools.partial(bert.loss_fn, cfg=cfg, tp_axis="tp"),
            mesh=mesh, in_specs=(bert.param_specs(cfg), (P(), P(), P())),
            out_specs=P(),
        )(params, (tokens, tokens, loss_mask))
        np.testing.assert_allclose(float(loss), float(ref), atol=2e-4,
                                   rtol=2e-4)

    def test_bidirectional(self):
        """Unlike GPT-2, early positions DO see later-token changes."""
        cfg = bert.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), cfg)
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                cfg.vocab_size)
        t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab_size)
        h1 = bert.forward(params, t1, cfg, tp_axis=None)
        h2 = bert.forward(params, t2, cfg, tp_axis=None)
        assert not np.allclose(np.asarray(h1[0, :10]), np.asarray(h2[0, :10]))


# ----------------------------------------------------------- resnet / dcgan


class TestVision:
    def test_resnet_forward(self):
        model = resnet.tiny()
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        logits = model.apply(variables, x, train=False)
        assert logits.shape == (2, 10)

    def test_resnet_train_updates_stats(self):
        model = resnet.tiny()
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        _, new_state = model.apply(variables, x, train=True,
                                   mutable=["batch_stats"])
        old = jax.tree_util.tree_leaves(variables["batch_stats"])
        new = jax.tree_util.tree_leaves(new_state["batch_stats"])
        assert any(not np.allclose(np.asarray(a), np.asarray(b))
                   for a, b in zip(old, new))

    def test_dcgan_shapes(self):
        g = dcgan.Generator(width=8)
        d = dcgan.Discriminator(width=8)
        z = jax.random.normal(jax.random.PRNGKey(0), (2, 100))
        gv = g.init(jax.random.PRNGKey(1), z, train=False)
        img = g.apply(gv, z, train=False)
        assert img.shape == (2, 32, 32, 3)
        assert float(jnp.max(jnp.abs(img))) <= 1.0
        dv = d.init(jax.random.PRNGKey(2), img, train=False)
        logit = d.apply(dv, img, train=False)
        assert logit.shape == (2,)


# --------------------------------------------------------------------- mlp


class TestMLP:
    def test_train_loss_decreases(self):
        cfg = mlp.MLPConfig(sizes=(16, 32, 4))
        params = mlp.init_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
        y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 4)
        tx = fused_adam(lr=1e-2)
        state = tx.init(params)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(mlp.loss_fn)(params, (x, y), cfg)
            updates, state = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), state, loss

        first = None
        for _ in range(20):
            params, state, loss = step(params, state)
            first = loss if first is None else first
        assert float(loss) < float(first)


# --------------------------------------------------------------- llama MoE


class TestLlamaMoE:
    """Mixtral-style routed experts in the flagship (cfg.num_experts > 0;
    experts over 'ep', orthogonal to tp)."""

    def _cfg(self, **over):
        kw = dict(num_experts=4, moe_top_k=2, moe_capacity_factor=8.0)
        kw.update(over)
        return llama.tiny(**kw)

    def test_forward_shape_and_aux(self):
        cfg = self._cfg()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        assert params["layers"]["wg"].shape == (
            cfg.num_layers, 4, cfg.hidden_size, cfg.intermediate_size)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        logits, aux = llama.forward_with_aux(
            params, tokens, cfg, tp_axis=None, cp_axis=None, ep_axis=None)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert float(aux) > 0

    def test_train_loss_decreases(self):
        cfg = self._cfg()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                    cfg.vocab_size)
        batch = (tokens, jnp.roll(tokens, -1, -1))
        tx = fused_adam(lr=3e-3)
        state = tx.init(params)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(llama.loss_fn)(
                params, batch, cfg, tp_axis=None, cp_axis=None,
                ep_axis=None)
            updates, state = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), state, loss

        first = None
        for _ in range(10):
            params, state, loss = step(params, state)
            first = loss if first is None else first
        assert float(loss) < float(first)

    def test_ep_parity(self):
        """dp=1 x ep=4 expert-parallel loss == single-device loss (generous
        capacity so nothing drops)."""
        cfg = self._cfg(num_experts=8)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref = llama.loss_fn(params, (tokens, jnp.roll(tokens, -1, -1)),
                            cfg, tp_axis=None, cp_axis=None, ep_axis=None)

        mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
        pspecs = llama.param_specs(cfg, tp_axis=None)

        def fn(params, tokens):
            loss = llama.loss_fn(params, (tokens, jnp.roll(tokens, -1, -1)),
                                 cfg, tp_axis=None, cp_axis=None,
                                 ep_axis="ep")
            return jax.lax.pmean(loss, "ep")

        loss = shard_map(
            fn, mesh=mesh, in_specs=(pspecs, P()), out_specs=P(),
        )(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)
