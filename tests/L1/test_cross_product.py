"""L1 cross-product: amp opt-level x model x optimizer x DDP
(ref tests/L1/cross_product/run.sh + tests/L1/common/main_amp.py:1-526).

Fast tier (default): a representative slice — every opt level on mlp,
every optimizer at O2, one transformer + one conv model at O0/O2, the
loss-scale variants, and a DDP-vs-single check. Full matrix (every
combination, 50 steps) runs under ``-m slow`` — the CI analog of the
reference's full cross_product sweep.
"""

import numpy as np
import pytest

from tests.L1.l1_harness import (
    assert_decreased,
    assert_tracks,
    baseline_curve,
    llama_pp_tp_curve,
    llama_single_curve,
    raw_fp32_curve,
    train_curve,
)

STEPS = 50

# bf16 forward + fp32 loss: curves track fp32 closely at these scales;
# resnet's BN statistics compound rounding faster, hence the looser bound
TOL = {"O0": 1e-6, "O1": 0.08, "O2": 0.08, "O3": 0.15}
TOL_RESNET = {"O0": 1e-6, "O1": 0.15, "O2": 0.2, "O3": 0.3}


def _check(model, opt_level, tx_name, steps=STEPS, ddp=False):
    curve = train_curve(model, opt_level, tx_name, steps=steps, ddp=ddp)
    ref = baseline_curve(model, tx_name, steps=steps, ddp=ddp)
    assert_decreased(ref, f"{model}/{tx_name}/O0")
    tol = (TOL_RESNET if model == "resnet" else TOL)[opt_level]
    assert_tracks(curve, ref, tol,
                  f"{model}/{tx_name}/{opt_level}{'/ddp' if ddp else ''}")
    assert_decreased(curve, f"{model}/{tx_name}/{opt_level}")
    return curve


# ------------------------------------------------------------- fast tier


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
def test_mlp_adam_all_opt_levels(opt_level):
    _check("mlp", opt_level, "adam")


@pytest.mark.parametrize("tx_name", ["adam", "lamb", "sgd"])
def test_mlp_o2_all_optimizers(tx_name):
    _check("mlp", "O2", tx_name)


@pytest.mark.parametrize("model", ["gpt2", "bert", "resnet"])
def test_models_o2_adam(model):
    _check(model, "O2", "adam")


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_moe_llama_adam(opt_level):
    """Routed-expert (Mixtral-style) training through the amp matrix:
    the router's top-k dispatch + aux balance loss must track the fp32
    curve like the dense models do."""
    _check("moe", opt_level, "adam", steps=30)


@pytest.mark.parametrize("loss_scale", [1.0, 128.0, "dynamic"])
def test_o2_loss_scale_variants(loss_scale):
    """run_test.sh's loss_scales axis: static 1.0 / static 128 / dynamic
    must land the same curve (scaling cancels exactly in fp32 unscale)."""
    curve = train_curve("mlp", "O2", "adam", steps=STEPS,
                        loss_scale=loss_scale)
    ref = baseline_curve("mlp", "adam", steps=STEPS)
    assert_tracks(curve, ref, TOL["O2"], f"mlp/O2/scale={loss_scale}")


def test_ddp_matches_single_o0():
    """The distributed leg: dp=4 sharded global batch + pmean grads must
    reproduce the single-device curve over the same data (fp32 ->
    reduction order is the only difference)."""
    single = baseline_curve("mlp", "adam", steps=STEPS)
    ddp = train_curve("mlp", "O0", "adam", steps=STEPS, ddp=True)
    assert_tracks(ddp, single, 1e-4, "mlp/O0/ddp-vs-single")


def test_ddp_matches_single_o2():
    single = train_curve("mlp", "O2", "adam", steps=STEPS)
    ddp = train_curve("mlp", "O2", "adam", steps=STEPS, ddp=True)
    assert_tracks(ddp, single, 0.05, "mlp/O2/ddp-vs-single")


def test_o0_is_exact_fp32():
    """O0 through the amp machinery must be bit-identical to a plain
    fp32 loop built WITHOUT amp — no scaler/policy/scaled_update (amp
    disabled = complete no-op, ref frontend contract)."""
    a = train_curve("mlp", "O0", "adam", steps=10)
    b = raw_fp32_curve("mlp", "adam", steps=10)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_llama_pp_tp_amp_adam(opt_level):
    """The flagship-parallelism leg: llama-tiny trained over a pp=2 x
    tp=2 mesh (1F1B pipeline + tensor/sequence parallel + vocab-parallel
    CE + amp) must track the single-device run of the same config over
    the same data (ref tests/L1/common/main_amp.py distributed legs)."""
    single = llama_single_curve(opt_level, steps=25)
    meshed = llama_pp_tp_curve(opt_level, steps=25)
    assert_decreased(single, f"llama/{opt_level}/single")
    assert_decreased(meshed, f"llama/{opt_level}/pp2xtp2")
    assert_tracks(meshed, single, 0.08,
                  f"llama/{opt_level}/pp2xtp2-vs-single")


# ------------------------------------------------------------- full matrix


@pytest.mark.slow
@pytest.mark.parametrize("model", ["mlp", "gpt2", "bert", "resnet"])
@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
@pytest.mark.parametrize("tx_name", ["adam", "lamb", "sgd"])
def test_full_cross_product(model, opt_level, tx_name):
    _check(model, opt_level, tx_name)


@pytest.mark.slow
@pytest.mark.parametrize("model", ["mlp", "gpt2"])
@pytest.mark.parametrize("opt_level", ["O0", "O2"])
def test_full_ddp_cross_product(model, opt_level):
    single = train_curve(model, opt_level, "adam", steps=STEPS)
    ddp = train_curve(model, opt_level, "adam", steps=STEPS, ddp=True)
    tol = 1e-4 if opt_level == "O0" else 0.05
    assert_tracks(ddp, single, tol, f"{model}/{opt_level}/ddp-vs-single")
