"""L1 cross-product integration harness.

The repo's analog of the reference's end-to-end precision matrix
(ref tests/L1/cross_product/run.sh, tests/L1/common/main_amp.py:1-526,
tests/L1/common/compare.py:1): train real (tiny) models through the
public amp + fused-optimizer APIs across opt-level x model x optimizer
x loss-scale x DDP, record the per-step loss curve, and compare every
mixed-precision run against the fp32/O0 run of the same (model,
optimizer) pair. The reference compares saved torch loss logs bitwise
between with/without-extension runs; on TPU the analog axis is
"amp curve must track the fp32 curve within bf16 tolerance" plus
"DDP over the dp mesh must track single-device over the same global
batch".

Everything runs on the 8-device virtual CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import bert, gpt2, mlp, resnet
from apex_tpu.optimizers import fused_adam, fused_lamb, fused_sgd
from apex_tpu.parallel import sync_autodiff_gradients

GLOBAL_BATCH = 16
N_BATCHES = 8  # distinct batches, cycled — every run sees the same data


# --------------------------------------------------------------- model zoo


def _mlp_adapter():
    cfg = mlp.MLPConfig(sizes=(32, 64, 64, 10))

    def init(key):
        return mlp.init_params(key, cfg), None

    def loss(params, aux, batch):
        return mlp.loss_fn(params, batch, cfg), aux

    def make_batch(key):
        kx, ky = jax.random.split(key)
        x = jax.random.normal(kx, (GLOBAL_BATCH, 32), jnp.float32)
        y = jax.random.randint(ky, (GLOBAL_BATCH,), 0, 10)
        return x, y

    return init, loss, make_batch


def _gpt2_adapter():
    cfg = gpt2.tiny(num_layers=2)

    def init(key):
        return gpt2.init_params(key, cfg), None

    def loss(params, aux, batch):
        tokens, targets = batch
        return gpt2.loss_fn(params, (tokens, targets), cfg,
                            tp_axis=None), aux

    def make_batch(key):
        tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
        return tokens, tokens

    return init, loss, make_batch


def _bert_adapter():
    cfg = bert.tiny(num_layers=2)

    def init(key):
        return bert.init_params(key, cfg), None

    def loss(params, aux, batch):
        return bert.loss_fn(params, batch, cfg, tp_axis=None), aux

    def make_batch(key):
        km, kt = jax.random.split(key)
        tokens = jax.random.randint(kt, (4, 32), 4, cfg.vocab_size)
        mask = jax.random.bernoulli(km, 0.25, (4, 32)).astype(jnp.float32)
        return tokens, tokens, mask

    return init, loss, make_batch


def _resnet_adapter(half=False):
    model = resnet.tiny(axis_name=None,
                        dtype=jnp.bfloat16 if half else jnp.float32)
    x0 = jnp.ones((2, 32, 32, 3), jnp.float32)

    def init(key):
        variables = model.init(key, x0, train=False)
        return variables["params"], variables["batch_stats"]

    def loss(params, batch_stats, batch):
        x, y = batch
        logits, mut = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=True,
            mutable=["batch_stats"])
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y).mean()
        return l, mut["batch_stats"]

    def make_batch(key):
        kx, ky = jax.random.split(key)
        x = jax.random.normal(kx, (GLOBAL_BATCH, 32, 32, 3), jnp.float32)
        y = jax.random.randint(ky, (GLOBAL_BATCH,), 0, 10)
        return x, y

    return init, loss, make_batch


def _moe_llama_adapter():
    """Mixtral-style MoE llama (4 experts, top-2): the routed-expert
    training path through the L1 amp x optimizer matrix. Single-device
    (ep_axis=None) — expert sharding is exercised by the dryruns; L1
    checks the amp curves."""
    from apex_tpu.models import llama

    cfg = llama.tiny(num_layers=2, num_experts=4,
                     moe_capacity_factor=2.0)

    def init(key):
        return llama.init_params(key, cfg), None

    def loss(params, aux, batch):
        return llama.loss_fn(params, batch, cfg, tp_axis=None,
                             cp_axis=None, ep_axis=None,
                             remat=False), aux

    def make_batch(key):
        tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
        return tokens, jnp.roll(tokens, -1, axis=-1)

    return init, loss, make_batch


def get_model(name, opt_level):
    if name == "mlp":
        return _mlp_adapter()
    if name == "gpt2":
        return _gpt2_adapter()
    if name == "bert":
        return _bert_adapter()
    if name == "moe":
        return _moe_llama_adapter()
    if name == "resnet":
        # the flax module's compute dtype is a model attribute, the
        # L1 analog of the reference rebuilding resnet under amp
        return _resnet_adapter(half=opt_level in ("O2", "O3"))
    raise ValueError(name)


def make_tx(name, lr=3e-3):
    if name == "adam":
        return fused_adam(lr=lr)
    if name == "lamb":
        return fused_lamb(lr=lr, weight_decay=0.0)
    if name == "sgd":
        return fused_sgd(lr=lr * 3, momentum=0.9)
    raise ValueError(name)


# ------------------------------------------------------------ train runner


def _cast_for_forward(handle, opt_level, params, batch):
    """The dtype story of each opt level, functional form: O0 fp32;
    O1 boundary-casts params+inputs per call (weights STAY fp32 between
    steps); O2/O3 cast the model (O2 keeps norm params fp32 and holds
    fp32 masters — here the master IS the optimizer-visible tree)."""
    if opt_level == "O0":
        return params, batch
    cast_batch = tuple(
        b.astype(handle.policy.compute_dtype)
        if jnp.issubdtype(b.dtype, jnp.floating) else b for b in batch)
    if opt_level == "O1":
        return handle.policy.cast_to_compute(params), cast_batch
    return handle.policy.cast_model(params), cast_batch


def train_curve(model_name, opt_level, tx_name, steps=50, ddp=False,
                loss_scale=None, seed=0):
    """Train and return the per-step loss curve as a float numpy array.

    ``ddp=True`` runs the identical step inside shard_map over a 4-way
    'dp' mesh with the global batch sharded and grads pmean-synced —
    the analog of the reference's --nproc_per_node=2 distributed leg.
    """
    handle = amp.initialize(opt_level=opt_level, loss_scale=loss_scale,
                            verbosity=0)
    init, loss_fn, make_batch = get_model(model_name, opt_level)
    params, aux = init(jax.random.PRNGKey(seed))

    if opt_level == "O3":
        # pure half: no fp32 master copy survives (ref O3 semantics) —
        # the optimizer state itself is built over bf16 params
        params = handle.policy.cast_model(params)

    tx = make_tx(tx_name)
    opt_state = tx.init(params)
    sstate = handle.scaler.init()

    batches = [make_batch(jax.random.PRNGKey(1000 + i))
               for i in range(N_BATCHES)]

    def step_body(params, aux, opt_state, sstate, batch, axis_name=None):
        def scaled(p):
            fwd_p, fwd_b = _cast_for_forward(handle, opt_level, p, batch)
            l, new_aux = loss_fn(fwd_p, aux, fwd_b)
            return handle.scaler.scale_loss(l, sstate), (l, new_aux)

        grads, (l, new_aux) = jax.grad(scaled, has_aux=True)(params)
        if axis_name is not None:
            # vma-aware: the fused-kernel custom_vjp grads arrive local
            # while plain grads arrive auto-psummed (distributed.py note)
            grads = sync_autodiff_gradients(grads, axis_name=axis_name)
            l = jax.lax.pmean(l, axis_name)
        updates, opt_state, sstate, _ = handle.scaled_update(
            tx, grads, opt_state, params, sstate)
        params = optax.apply_updates(params, updates)
        return params, new_aux, opt_state, sstate, l

    if not ddp:
        step = jax.jit(step_body)
    else:
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        none_aux = aux is None

        def sharded(params, aux, opt_state, sstate, batch):
            return step_body(params, aux if not none_aux else None,
                             opt_state, sstate, batch, axis_name="dp")

        batch_spec = jax.tree_util.tree_map(lambda _: P("dp"), batches[0])
        rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        # check_vma left ON: replicated-param grads arrive auto-psummed
        # (the library's DDP pattern, parallel/distributed.py module note)
        # and average_reduced turns them into the global-batch mean
        step = jax.jit(shard_map(
            sharded, mesh=mesh,
            in_specs=(rep(params), rep(aux), rep(opt_state), rep(sstate),
                      batch_spec),
            out_specs=(rep(params), rep(aux), rep(opt_state), rep(sstate),
                       P())))

    losses = []
    for i in range(steps):
        params, aux, opt_state, sstate, l = step(
            params, aux, opt_state, sstate, batches[i % N_BATCHES])
        losses.append(l)
    return np.asarray(jax.device_get(losses), np.float64)


# ---------------------------------------------------- llama pp x tp leg


def _llama_setup(seed=0):
    """Shared tiny-llama config + data for the flagship-parallelism leg
    (VERDICT r4 next-step #6: the flagship config previously only ever
    took one dryrun step or untrained parity tests)."""
    from apex_tpu.models import llama

    cfg = llama.tiny(num_layers=4, num_heads=4, num_kv_heads=2,
                     hidden_size=64, intermediate_size=128, vocab_size=128)
    params = llama.init_params(jax.random.PRNGKey(seed), cfg)
    M, mb, s = 2, 4, 16  # microbatches x per-mb batch x seq
    batches = []
    for i in range(N_BATCHES):
        tokens = jax.random.randint(jax.random.PRNGKey(2000 + i),
                                    (M, mb, s), 0, cfg.vocab_size)
        batches.append((tokens, jnp.roll(tokens, -1, axis=-1)))
    return llama, cfg, params, batches, (M, mb, s)


def _fwd_cast(handle, opt_level, tree):
    if opt_level == "O1":
        return handle.policy.cast_to_compute(tree)
    if opt_level in ("O2", "O3"):
        return handle.policy.cast_model(tree)
    return tree


def llama_single_curve(opt_level, steps=25, seed=0):
    """Single-device llama train curve (fp32 masters, amp casting)."""
    handle = amp.initialize(opt_level=opt_level, verbosity=0)
    llama, cfg, params, batches, (M, mb, s) = _llama_setup(seed)
    tx = make_tx("adam")
    opt_state = tx.init(params)
    sstate = handle.scaler.init()

    def step(params, opt_state, sstate, batch):
        tokens, targets = batch

        def scaled(p):
            l = llama.loss_fn(
                _fwd_cast(handle, opt_level, p),
                (tokens.reshape(M * mb, s), targets.reshape(M * mb, s)),
                cfg, tp_axis=None, cp_axis=None)
            return handle.scaler.scale_loss(l, sstate), l

        grads, l = jax.grad(scaled, has_aux=True)(params)
        updates, opt_state, sstate, _ = handle.scaled_update(
            tx, grads, opt_state, params, sstate)
        params = optax.apply_updates(params, updates)
        return params, opt_state, sstate, l

    jstep = jax.jit(step)
    losses = []
    for i in range(steps):
        params, opt_state, sstate, l = jstep(
            params, opt_state, sstate, batches[i % N_BATCHES])
        losses.append(l)
    return np.asarray(jax.device_get(losses), np.float64)


def llama_pp_tp_curve(opt_level, steps=25, seed=0):
    """The same llama training over a pp=2 x tp=2 mesh: collective-1F1B
    pipeline + tensor parallel with sequence parallelism + vocab-parallel
    CE, amp-cast per step, overflow vote across both axes."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipelined_forward,
    )
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
    )
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    handle = amp.initialize(opt_level=opt_level, verbosity=0)
    llama, cfg, params, batches, (M, mb, s) = _llama_setup(seed)
    pp = tp = 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(pp, tp), ("pp", "tp"))
    stage_params = llama.split_stages(params, pp)
    io_params = {k: v for k, v in params.items() if k != "layers"}
    tx = make_tx("adam")

    def _psum(x, ax):
        return jax.lax.psum(_to_varying(x, ax), ax)

    def train_step(stage_params, io_params, opt_state, sstate, tokens,
                   targets):
        pp_rank = jax.lax.axis_index("pp")
        pp_size = jax.lax.axis_size("pp")

        def vary_all(t):
            for ax in ("pp", "tp"):
                t = jax.tree_util.tree_map(
                    lambda a, ax=ax: _to_varying(a, ax), t)
            return t

        def scaled_loss(trees):
            stage, io = trees
            stage = jax.tree_util.tree_map(lambda a: a[0], stage)
            stage, io = vary_all(stage), vary_all(io)
            stage = _fwd_cast(handle, opt_level, stage)
            io = _fwd_cast(handle, opt_level, io)

            def embed_mb(tok):
                return llama.embed(io, tok, cfg, tp_axis="tp",
                                   sequence_parallel=True)

            x_mb = vary_all(jax.vmap(embed_mb)(tokens))
            positions = llama._positions(mb, s, None)

            def stage_fn(sp, x):
                return llama.stage_fn(sp, x, cfg, positions, tp_axis="tp",
                                      cp_axis=None, sequence_parallel=True)

            outs = pipelined_forward(stage_fn, stage, x_mb,
                                     axis_name="pp", remat=True)

            def mb_loss(o, t):
                logits = llama.lm_head(io, o, cfg, tp_axis="tp",
                                       sequence_parallel=True)
                return jnp.mean(
                    vocab_parallel_cross_entropy(logits, t, axis_name="tp"))

            losses = jax.vmap(mb_loss)(outs, targets)
            local = jnp.where(pp_rank == pp_size - 1, jnp.mean(losses), 0.0)
            loss = jax.lax.psum(local, "pp")
            return handle.scaler.scale_loss(loss, sstate), loss

        (_, loss), (g_stage, g_io) = jax.value_and_grad(
            scaled_loss, has_aux=True)((stage_params, io_params))

        # io params are pp-replicated but only first/last stages produce
        # their grads; norm params are tp-replicated but see different
        # sequence chunks in sp mode (Megatron sp grad allreduce)
        g_io = jax.tree_util.tree_map(lambda g: _psum(g, "pp"), g_io)
        g_stage = {k: (_psum(v, "tp") if k.endswith("norm") else v)
                   for k, v in g_stage.items()}
        g_io = {k: (_psum(v, "tp") if k == "final_norm" else v)
                for k, v in g_io.items()}

        grads = {"stage": g_stage, "io": g_io}
        params_t = {"stage": stage_params, "io": io_params}
        updates, opt_state, sstate, _ = handle.scaled_update(
            tx, grads, opt_state, params_t, sstate,
            overflow_reduce_axes=("pp", "tp"))
        new_params = jax.tree_util.tree_map(jnp.add, params_t, updates)
        loss = jax.lax.pmean(loss, "tp")
        return (new_params["stage"], new_params["io"], opt_state, sstate,
                loss)

    lp = llama.param_specs(cfg)["layers"]
    stage_specs = {k: P("pp", *lp[k]) for k in lp}
    io_specs = {"embed": P("tp", None), "final_norm": P(),
                "lm_head": P(None, "tp")}
    sstate0 = handle.scaler.init()
    sstate_specs = jax.tree_util.tree_map(lambda _: P(), sstate0)

    from apex_tpu.optimizers import opt_partition_specs

    with mesh:
        opt_state = tx.init({"stage": stage_params, "io": io_params})
        opt_specs = opt_partition_specs(
            tx, {"stage": stage_params, "io": io_params},
            {"stage": stage_specs, "io": io_specs})

        step = jax.jit(shard_map(
            train_step, mesh=mesh,
            in_specs=(stage_specs, io_specs, opt_specs, sstate_specs,
                      P(), P()),
            out_specs=(stage_specs, io_specs, opt_specs, sstate_specs,
                       P()),
        ))
        losses = []
        sstate = sstate0
        for i in range(steps):
            tokens, targets = batches[i % N_BATCHES]
            stage_params, io_params, opt_state, sstate, l = step(
                stage_params, io_params, opt_state, sstate, tokens,
                targets)
            losses.append(l)
    return np.asarray(jax.device_get(losses), np.float64)


def raw_fp32_curve(model_name, tx_name, steps=50, seed=0):
    """Plain fp32 loop with NO amp machinery at all — no scaler, no
    policy, no scaled_update, just grad → tx.update → apply_updates.
    The ground truth the 'O0 is a complete no-op' contract is checked
    against (an O0 run compared to another O0 run would only prove
    determinism)."""
    init, loss_fn, make_batch = get_model(model_name, "O0")
    params, aux = init(jax.random.PRNGKey(seed))
    tx = make_tx(tx_name)
    opt_state = tx.init(params)
    batches = [make_batch(jax.random.PRNGKey(1000 + i))
               for i in range(N_BATCHES)]

    def step_body(params, aux, opt_state, batch):
        def fwd(p):
            l, new_aux = loss_fn(p, aux, batch)
            return l, (l, new_aux)

        grads, (l, new_aux) = jax.grad(fwd, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_aux, opt_state, l

    step = jax.jit(step_body)
    losses = []
    for i in range(steps):
        params, aux, opt_state, l = step(params, aux, opt_state,
                                         batches[i % N_BATCHES])
        losses.append(l)
    return np.asarray(jax.device_get(losses), np.float64)


@functools.lru_cache(maxsize=None)
def baseline_curve(model_name, tx_name, steps=50, ddp=False):
    """The fp32/O0 run every amp config is compared against
    (the reference's saved-baseline role, compare.py --use_baseline)."""
    return train_curve(model_name, "O0", tx_name, steps=steps, ddp=ddp)


# ------------------------------------------------------------- comparators


def assert_decreased(curve, name=""):
    first = float(np.mean(curve[:3]))
    last = float(np.mean(curve[-3:]))
    assert last < first, f"{name}: loss did not decrease ({first} -> {last})"


def assert_tracks(curve, ref, rel_tol, name=""):
    """Mean relative deviation between two loss curves (the compare.py
    closeness check, with bf16 tolerance instead of bitwise equality).
    The denominator is floored at 10% of the initial loss so the metric
    stays meaningful when tiny models memorize the 8-batch dataset and
    the absolute loss (hence the naive relative error) goes to ~0."""
    curve, ref = np.asarray(curve), np.asarray(ref)
    floor = 0.1 * abs(float(ref[0])) + 1e-6
    rel = np.abs(curve - ref) / np.maximum(np.abs(ref), floor)
    mean_rel = float(np.mean(rel))
    assert mean_rel < rel_tol, (
        f"{name}: curve deviates from reference by {mean_rel:.4f} "
        f"(tol {rel_tol}); curve[:5]={curve[:5]}, ref[:5]={ref[:5]}")
