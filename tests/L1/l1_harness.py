"""L1 cross-product integration harness.

The repo's analog of the reference's end-to-end precision matrix
(ref tests/L1/cross_product/run.sh, tests/L1/common/main_amp.py:1-526,
tests/L1/common/compare.py:1): train real (tiny) models through the
public amp + fused-optimizer APIs across opt-level x model x optimizer
x loss-scale x DDP, record the per-step loss curve, and compare every
mixed-precision run against the fp32/O0 run of the same (model,
optimizer) pair. The reference compares saved torch loss logs bitwise
between with/without-extension runs; on TPU the analog axis is
"amp curve must track the fp32 curve within bf16 tolerance" plus
"DDP over the dp mesh must track single-device over the same global
batch".

Everything runs on the 8-device virtual CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models import bert, gpt2, mlp, resnet
from apex_tpu.optimizers import fused_adam, fused_lamb, fused_sgd
from apex_tpu.parallel import sync_autodiff_gradients

GLOBAL_BATCH = 16
N_BATCHES = 8  # distinct batches, cycled — every run sees the same data


# --------------------------------------------------------------- model zoo


def _mlp_adapter():
    cfg = mlp.MLPConfig(sizes=(32, 64, 64, 10))

    def init(key):
        return mlp.init_params(key, cfg), None

    def loss(params, aux, batch):
        return mlp.loss_fn(params, batch, cfg), aux

    def make_batch(key):
        kx, ky = jax.random.split(key)
        x = jax.random.normal(kx, (GLOBAL_BATCH, 32), jnp.float32)
        y = jax.random.randint(ky, (GLOBAL_BATCH,), 0, 10)
        return x, y

    return init, loss, make_batch


def _gpt2_adapter():
    cfg = gpt2.tiny(num_layers=2)

    def init(key):
        return gpt2.init_params(key, cfg), None

    def loss(params, aux, batch):
        tokens, targets = batch
        return gpt2.loss_fn(params, (tokens, targets), cfg,
                            tp_axis=None), aux

    def make_batch(key):
        tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
        return tokens, tokens

    return init, loss, make_batch


def _bert_adapter():
    cfg = bert.tiny(num_layers=2)

    def init(key):
        return bert.init_params(key, cfg), None

    def loss(params, aux, batch):
        return bert.loss_fn(params, batch, cfg, tp_axis=None), aux

    def make_batch(key):
        km, kt = jax.random.split(key)
        tokens = jax.random.randint(kt, (4, 32), 4, cfg.vocab_size)
        mask = jax.random.bernoulli(km, 0.25, (4, 32)).astype(jnp.float32)
        return tokens, tokens, mask

    return init, loss, make_batch


def _resnet_adapter(half=False):
    model = resnet.tiny(axis_name=None,
                        dtype=jnp.bfloat16 if half else jnp.float32)
    x0 = jnp.ones((2, 32, 32, 3), jnp.float32)

    def init(key):
        variables = model.init(key, x0, train=False)
        return variables["params"], variables["batch_stats"]

    def loss(params, batch_stats, batch):
        x, y = batch
        logits, mut = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=True,
            mutable=["batch_stats"])
        l = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y).mean()
        return l, mut["batch_stats"]

    def make_batch(key):
        kx, ky = jax.random.split(key)
        x = jax.random.normal(kx, (GLOBAL_BATCH, 32, 32, 3), jnp.float32)
        y = jax.random.randint(ky, (GLOBAL_BATCH,), 0, 10)
        return x, y

    return init, loss, make_batch


def get_model(name, opt_level):
    if name == "mlp":
        return _mlp_adapter()
    if name == "gpt2":
        return _gpt2_adapter()
    if name == "bert":
        return _bert_adapter()
    if name == "resnet":
        # the flax module's compute dtype is a model attribute, the
        # L1 analog of the reference rebuilding resnet under amp
        return _resnet_adapter(half=opt_level in ("O2", "O3"))
    raise ValueError(name)


def make_tx(name, lr=3e-3):
    if name == "adam":
        return fused_adam(lr=lr)
    if name == "lamb":
        return fused_lamb(lr=lr, weight_decay=0.0)
    if name == "sgd":
        return fused_sgd(lr=lr * 3, momentum=0.9)
    raise ValueError(name)


# ------------------------------------------------------------ train runner


def _cast_for_forward(handle, opt_level, params, batch):
    """The dtype story of each opt level, functional form: O0 fp32;
    O1 boundary-casts params+inputs per call (weights STAY fp32 between
    steps); O2/O3 cast the model (O2 keeps norm params fp32 and holds
    fp32 masters — here the master IS the optimizer-visible tree)."""
    if opt_level == "O0":
        return params, batch
    cast_batch = tuple(
        b.astype(handle.policy.compute_dtype)
        if jnp.issubdtype(b.dtype, jnp.floating) else b for b in batch)
    if opt_level == "O1":
        return handle.policy.cast_to_compute(params), cast_batch
    return handle.policy.cast_model(params), cast_batch


def train_curve(model_name, opt_level, tx_name, steps=50, ddp=False,
                loss_scale=None, seed=0):
    """Train and return the per-step loss curve as a float numpy array.

    ``ddp=True`` runs the identical step inside shard_map over a 4-way
    'dp' mesh with the global batch sharded and grads pmean-synced —
    the analog of the reference's --nproc_per_node=2 distributed leg.
    """
    handle = amp.initialize(opt_level=opt_level, loss_scale=loss_scale,
                            verbosity=0)
    init, loss_fn, make_batch = get_model(model_name, opt_level)
    params, aux = init(jax.random.PRNGKey(seed))

    if opt_level == "O3":
        # pure half: no fp32 master copy survives (ref O3 semantics) —
        # the optimizer state itself is built over bf16 params
        params = handle.policy.cast_model(params)

    tx = make_tx(tx_name)
    opt_state = tx.init(params)
    sstate = handle.scaler.init()

    batches = [make_batch(jax.random.PRNGKey(1000 + i))
               for i in range(N_BATCHES)]

    def step_body(params, aux, opt_state, sstate, batch, axis_name=None):
        def scaled(p):
            fwd_p, fwd_b = _cast_for_forward(handle, opt_level, p, batch)
            l, new_aux = loss_fn(fwd_p, aux, fwd_b)
            return handle.scaler.scale_loss(l, sstate), (l, new_aux)

        grads, (l, new_aux) = jax.grad(scaled, has_aux=True)(params)
        if axis_name is not None:
            # vma-aware: the fused-kernel custom_vjp grads arrive local
            # while plain grads arrive auto-psummed (distributed.py note)
            grads = sync_autodiff_gradients(grads, axis_name=axis_name)
            l = jax.lax.pmean(l, axis_name)
        updates, opt_state, sstate, _ = handle.scaled_update(
            tx, grads, opt_state, params, sstate)
        params = optax.apply_updates(params, updates)
        return params, new_aux, opt_state, sstate, l

    if not ddp:
        step = jax.jit(step_body)
    else:
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        none_aux = aux is None

        def sharded(params, aux, opt_state, sstate, batch):
            return step_body(params, aux if not none_aux else None,
                             opt_state, sstate, batch, axis_name="dp")

        batch_spec = jax.tree_util.tree_map(lambda _: P("dp"), batches[0])
        rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        # check_vma left ON: replicated-param grads arrive auto-psummed
        # (the library's DDP pattern, parallel/distributed.py module note)
        # and average_reduced turns them into the global-batch mean
        step = jax.jit(shard_map(
            sharded, mesh=mesh,
            in_specs=(rep(params), rep(aux), rep(opt_state), rep(sstate),
                      batch_spec),
            out_specs=(rep(params), rep(aux), rep(opt_state), rep(sstate),
                       P())))

    losses = []
    for i in range(steps):
        params, aux, opt_state, sstate, l = step(
            params, aux, opt_state, sstate, batches[i % N_BATCHES])
        losses.append(l)
    return np.asarray(jax.device_get(losses), np.float64)


def raw_fp32_curve(model_name, tx_name, steps=50, seed=0):
    """Plain fp32 loop with NO amp machinery at all — no scaler, no
    policy, no scaled_update, just grad → tx.update → apply_updates.
    The ground truth the 'O0 is a complete no-op' contract is checked
    against (an O0 run compared to another O0 run would only prove
    determinism)."""
    init, loss_fn, make_batch = get_model(model_name, "O0")
    params, aux = init(jax.random.PRNGKey(seed))
    tx = make_tx(tx_name)
    opt_state = tx.init(params)
    batches = [make_batch(jax.random.PRNGKey(1000 + i))
               for i in range(N_BATCHES)]

    def step_body(params, aux, opt_state, batch):
        def fwd(p):
            l, new_aux = loss_fn(p, aux, batch)
            return l, (l, new_aux)

        grads, (l, new_aux) = jax.grad(fwd, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_aux, opt_state, l

    step = jax.jit(step_body)
    losses = []
    for i in range(steps):
        params, aux, opt_state, l = step(params, aux, opt_state,
                                         batches[i % N_BATCHES])
        losses.append(l)
    return np.asarray(jax.device_get(losses), np.float64)


@functools.lru_cache(maxsize=None)
def baseline_curve(model_name, tx_name, steps=50, ddp=False):
    """The fp32/O0 run every amp config is compared against
    (the reference's saved-baseline role, compare.py --use_baseline)."""
    return train_curve(model_name, "O0", tx_name, steps=steps, ddp=ddp)


# ------------------------------------------------------------- comparators


def assert_decreased(curve, name=""):
    first = float(np.mean(curve[:3]))
    last = float(np.mean(curve[-3:]))
    assert last < first, f"{name}: loss did not decrease ({first} -> {last})"


def assert_tracks(curve, ref, rel_tol, name=""):
    """Mean relative deviation between two loss curves (the compare.py
    closeness check, with bf16 tolerance instead of bitwise equality).
    The denominator is floored at 10% of the initial loss so the metric
    stays meaningful when tiny models memorize the 8-batch dataset and
    the absolute loss (hence the naive relative error) goes to ~0."""
    curve, ref = np.asarray(curve), np.asarray(ref)
    floor = 0.1 * abs(float(ref[0])) + 1e-6
    rel = np.abs(curve - ref) / np.maximum(np.abs(ref), floor)
    mean_rel = float(np.mean(rel))
    assert mean_rel < rel_tol, (
        f"{name}: curve deviates from reference by {mean_rel:.4f} "
        f"(tol {rel_tol}); curve[:5]={curve[:5]}, ref[:5]={ref[:5]}")
