"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; all sharding/collective tests run
against XLA's host-platform device partitioning (SURVEY.md §4).

Note: the container's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already in the environment, so setting env vars here is
too late for the platform choice — it must go through jax.config. XLA_FLAGS
is still read at (lazy) backend initialization, which has not happened yet
when conftest runs.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Hermetic tuning cache: dispatch consults the persistent per-device
# tuning cache (apex_tpu.tuning), and a developer's real
# ~/.cache/apex_tpu/tuning_cache.json would change tile geometry and
# _KERNEL_AUTO verdicts under test (or, schema-drifted, error every
# dispatch). Point the whole suite at a fresh per-session path unless
# the invoker explicitly chose one; tests that need their own cache
# (tests/run_tuning) still monkeypatch over this.
if "APEX_TPU_TUNING_CACHE" not in os.environ:
    import tempfile

    os.environ["APEX_TPU_TUNING_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="apex_tpu_test_tuning_"),
        "tuning_cache.json")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end example tests")
