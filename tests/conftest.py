"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; all sharding/collective tests run
against XLA's host-platform device partitioning (SURVEY.md §4).

Note: the container's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already in the environment, so setting env vars here is
too late for the platform choice — it must go through jax.config. XLA_FLAGS
is still read at (lazy) backend initialization, which has not happened yet
when conftest runs.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Hermetic tuning cache: dispatch consults the persistent per-device
# tuning cache (apex_tpu.tuning), and a developer's real
# ~/.cache/apex_tpu/tuning_cache.json would change tile geometry and
# _KERNEL_AUTO verdicts under test (or, schema-drifted, error every
# dispatch). Point the whole suite at a fresh per-session path unless
# the invoker explicitly chose one; tests that need their own cache
# (tests/run_tuning) still monkeypatch over this.
if "APEX_TPU_TUNING_CACHE" not in os.environ:
    import tempfile

    os.environ["APEX_TPU_TUNING_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="apex_tpu_test_tuning_"),
        "tuning_cache.json")

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end example tests")
    config.addinivalue_line(
        "markers",
        "multidevice(n=8): needs an n-way (simulated) device mesh; "
        "skipped when the backend came up with fewer devices")


def pytest_runtest_setup(item):
    marker = item.get_closest_marker("multidevice")
    if marker is None:
        return
    need = marker.kwargs.get("n", marker.args[0] if marker.args else 8)
    have = len(jax.devices())
    if have < need:
        pytest.skip(f"needs {need} devices, backend has {have} "
                    f"(the 8-way simulated mesh failed to force)")


@pytest.fixture
def simulated_mesh_subprocess():
    """Shared multi-device harness (ISSUE 11): run a python snippet in
    a FRESH subprocess against an 8-way simulated CPU mesh
    (``apex_tpu.parallel.multiproc.simulated_mesh_env`` sets
    ``--xla_force_host_platform_device_count`` before the interpreter
    starts, so every comms path runs real collectives even where this
    conftest's in-process forcing never ran). Returns a callable
    ``run(code, n=8, timeout=300)`` -> CompletedProcess."""
    def run(code: str, n: int = 8, timeout: float = 300.0):
        from apex_tpu.parallel import multiproc

        return multiproc.run_simulated(
            [sys.executable, "-c", code], n=n, timeout=timeout)

    return run
