"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; all sharding/collective tests run
against XLA's host-platform device partitioning (SURVEY.md §4).

Note: the container's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already in the environment, so setting env vars here is
too late for the platform choice — it must go through jax.config. XLA_FLAGS
is still read at (lazy) backend initialization, which has not happened yet
when conftest runs.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end example tests")
